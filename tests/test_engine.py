"""Engine vs oracle differential tests — the reference's six-programs-one-input
methodology (SURVEY.md §4) automated, on 1x1 through RxC CPU meshes."""

import numpy as np
import pytest

from gol_tpu import engine, oracle
from gol_tpu.config import Convention, GameConfig
from gol_tpu.io import text_grid
from gol_tpu.parallel import make_mesh
from gol_tpu.parallel.mesh import validate_grid, topology_for, choose_mesh_shape

MESH_SHAPES = [(1, 1), (2, 2), (2, 4), (4, 2), (1, 8), (8, 1)]


def mesh_or_none(rows, cols):
    if (rows, cols) == (1, 1):
        return None
    return make_mesh(rows, cols)


class TestSingleDevice:
    def test_random_matches_oracle(self):
        g = text_grid.generate(64, 64, seed=3)
        cfg = GameConfig(gen_limit=50)
        got = engine.simulate(g, cfg)
        want = oracle.run(g, cfg)
        assert got.generations == want.generations == 50
        assert np.array_equal(got.grid, want.grid)

    def test_rectangular_grid(self):
        g = text_grid.generate(48, 24, seed=4)  # width=48, height=24
        cfg = GameConfig(gen_limit=20)
        got = engine.simulate(g, cfg)
        want = oracle.run(g, cfg)
        assert np.array_equal(got.grid, want.grid)

    def test_similarity_exit(self):
        block = np.zeros((8, 8), np.uint8)
        block[3:5, 3:5] = 1
        got = engine.simulate(block)
        assert got.generations == 2
        assert np.array_equal(got.grid, block)

    def test_empty_exit(self):
        lone = np.zeros((8, 8), np.uint8)
        lone[4, 4] = 1
        got = engine.simulate(lone)
        assert got.generations == 1
        assert got.grid.sum() == 0

    def test_all_dead_zero_generations(self):
        got = engine.simulate(np.zeros((8, 8), np.uint8))
        assert got.generations == 0

    def test_gen_limit_zero(self):
        g = text_grid.generate(8, 8, seed=0)
        got = engine.simulate(g, GameConfig(gen_limit=0))
        assert got.generations == 0
        assert np.array_equal(got.grid, g)

    def test_check_similarity_off(self):
        block = np.zeros((8, 8), np.uint8)
        block[3:5, 3:5] = 1
        got = engine.simulate(block, GameConfig(gen_limit=5, check_similarity=False))
        assert got.generations == 5


class TestCudaConvention:
    def test_random_matches_cuda_oracle(self):
        g = text_grid.generate(32, 32, seed=5)
        cfg = GameConfig(gen_limit=40, convention=Convention.CUDA)
        got = engine.simulate(g, cfg)
        want = oracle.run(g, cfg)
        assert got.generations == want.generations
        assert np.array_equal(got.grid, want.grid)

    def test_empty_exit_keeps_previous_generation(self):
        lone = np.zeros((8, 8), np.uint8)
        lone[4, 4] = 1
        got = engine.simulate(lone, GameConfig(convention=Convention.CUDA))
        assert got.generations == 0
        assert got.grid.sum() == 1

    def test_similarity_exit(self):
        block = np.zeros((8, 8), np.uint8)
        block[3:5, 3:5] = 1
        got = engine.simulate(block, GameConfig(convention=Convention.CUDA))
        assert got.generations == 2
        assert np.array_equal(got.grid, block)


class TestDistributed:
    @pytest.mark.parametrize("rows,cols", MESH_SHAPES)
    def test_random_matches_oracle_on_mesh(self, rows, cols):
        g = text_grid.generate(32, 32, seed=6)
        cfg = GameConfig(gen_limit=30)
        got = engine.simulate(g, cfg, mesh=mesh_or_none(rows, cols))
        want = oracle.run(g, cfg)
        assert got.generations == want.generations
        assert np.array_equal(got.grid, want.grid)

    def test_glider_crosses_shard_boundaries_and_wraps(self):
        # A glider travelling diagonally crosses every ppermute boundary and
        # the torus seam — the halo-exchange acid test (SURVEY.md §4d).
        g = np.zeros((16, 16), np.uint8)
        g[0, 1] = g[1, 2] = g[2, 0] = g[2, 1] = g[2, 2] = 1
        cfg = GameConfig(gen_limit=4 * 16, check_similarity=False)
        got = engine.simulate(g, cfg, mesh=make_mesh(2, 4))
        assert np.array_equal(got.grid, g)  # full wrap returns it home

    def test_similarity_exit_on_mesh(self):
        # Still life spanning a shard boundary: the similarity consensus must
        # agree across shards (psum vote, src/game_mpi_collective.c:98-109).
        block = np.zeros((8, 8), np.uint8)
        block[3:5, 3:5] = 1  # straddles the 2x2 mesh center seam
        got = engine.simulate(block, mesh=make_mesh(2, 2))
        assert got.generations == 2
        assert np.array_equal(got.grid, block)

    def test_empty_exit_on_mesh(self):
        lone = np.zeros((8, 8), np.uint8)
        lone[0, 0] = 1  # dies; exercises the alive psum vote
        got = engine.simulate(lone, mesh=make_mesh(2, 2))
        assert got.generations == 1
        assert got.grid.sum() == 0

    def test_cuda_convention_on_mesh(self):
        g = text_grid.generate(32, 32, seed=7)
        cfg = GameConfig(gen_limit=25, convention=Convention.CUDA)
        got = engine.simulate(g, cfg, mesh=make_mesh(2, 2))
        want = oracle.run(g, cfg)
        assert got.generations == want.generations
        assert np.array_equal(got.grid, want.grid)

    def test_indivisible_grid_rejected(self):
        g = text_grid.generate(30, 30, seed=0)
        with pytest.raises(ValueError, match="does not divide"):
            engine.simulate(g, mesh=make_mesh(4, 2))

    def test_determinism(self):
        g = text_grid.generate(32, 32, seed=8)
        cfg = GameConfig(gen_limit=20)
        a = engine.simulate(g, cfg, mesh=make_mesh(2, 2))
        b = engine.simulate(g, cfg, mesh=make_mesh(2, 2))
        assert np.array_equal(a.grid, b.grid)


def test_choose_mesh_shape():
    # Row-only (n, 1) is the default: the measured-fastest decomposition
    # (full-width shards skip the ghost-column machinery entirely).
    assert choose_mesh_shape(8) == (8, 1)
    assert choose_mesh_shape(16) == (16, 1)
    assert choose_mesh_shape(1) == (1, 1)
    assert choose_mesh_shape(7) == (7, 1)
    # Width-aware guard: past the temporal kernel's VMEM width cap
    # (_MAX_WORDS_T words per shard), just enough mesh columns are added to
    # keep the fast kernel eligible instead of silently falling to the
    # per-generation path.
    assert choose_mesh_shape(8, width=262144) == (8, 1)   # exactly at cap
    assert choose_mesh_shape(8, width=524288) == (4, 2)
    assert choose_mesh_shape(8, width=2097152) == (1, 8)
    assert choose_mesh_shape(16, width=524288) == (8, 2)
    # Prime device count: 7 columns — but only for widths 7 divides (the r3
    # rule suggested (1, 7) for ANY over-cap width, including ones
    # validate_grid would then reject; the width filter fixes that).
    assert choose_mesh_shape(7, width=917504) == (1, 7)


def test_choose_mesh_shape_cap_fallback_warns_via_warnings(recwarn):
    """ADVICE r5: the width-cap fallback must announce itself through
    ``warnings.warn`` (filterable, per-call-site deduped), never a raw
    stderr write from library code. One device on a grid no factorization
    can keep under the temporal kernel's width cap takes the fallback and
    warns RuntimeWarning; the in-cap path stays silent."""
    import warnings

    with pytest.warns(RuntimeWarning, match="width cap"):
        assert choose_mesh_shape(1, width=524288) == (1, 1)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any warning would raise
        assert choose_mesh_shape(8, width=262144) == (8, 1)


def test_choose_mesh_shape_height_aware(capsys):
    # Heights the row-only default cannot shard fall to the row-heaviest
    # factorization that divides the grid (advisor r3: the old near-square
    # default served grids like 100 rows on 8 devices; now (4, 2) does).
    assert choose_mesh_shape(8, height=100) == (4, 2)
    assert choose_mesh_shape(8, width=100, height=100) == (4, 2)
    assert choose_mesh_shape(8, height=25) == (1, 8)
    assert choose_mesh_shape(6, height=33, width=32) == (3, 2)
    # Nothing divides: keep (n, 1) so validate_grid raises its loud error
    # for the default mesh exactly as for an explicit one.
    assert choose_mesh_shape(8, width=30, height=21) == (8, 1)
    assert capsys.readouterr().err == ""


def test_choose_mesh_shape_warns_when_cap_unreachable():
    # No 8-device factorization brings a 2^22-wide shard under the temporal
    # width cap (needs 16 columns): fall back row-heaviest, but say so —
    # the silent ~2x kernel downgrade was an r3 advisor finding. Via
    # warnings.warn, not raw stderr (r4 advisor), so embedders can filter.
    with pytest.warns(RuntimeWarning, match="width cap.*--mesh"):
        assert choose_mesh_shape(8, width=4194304) == (8, 1)


def test_validate_grid_local_shape():
    topo = topology_for(make_mesh(2, 4))
    assert validate_grid(16, 32, topo) == (8, 8)


class TestBlockTermination:
    """Pins the blocked C-convention loop (engine._simulate_c_block): exits
    landing on every offset within the 16-generation vote block must report
    oracle-identical generation counts and grids."""

    @pytest.mark.parametrize("gen_limit", [1, 15, 16, 17, 31, 33, 48])
    def test_bound_straddles_blocks(self, gen_limit):
        g = text_grid.generate(64, 64, seed=5)  # soup: no early exit
        cfg = GameConfig(gen_limit=gen_limit)
        got = engine.simulate(g, cfg, kernel="packed")
        want = oracle.run(g, cfg)
        assert got.generations == want.generations == gen_limit
        assert np.array_equal(got.grid, want.grid)

    # Seeds chosen (by oracle search) so the early exits land on 12 distinct
    # offsets within the 16-generation block, both exit kinds represented.
    @pytest.mark.parametrize(
        "seed,density,exit_gen",
        [
            (60, 0.08, 17), (10, 0.28, 194), (4, 0.08, 3), (149, 0.18, 68),
            (34, 0.28, 149), (108, 0.08, 6), (218, 0.28, 119), (64, 0.08, 8),
            (119, 0.38, 122), (0, 0.08, 11), (88, 0.08, 29), (58, 0.28, 110),
        ],
    )
    def test_early_exits_at_varied_block_offsets(self, seed, density, exit_gen):
        g = text_grid.generate(32, 32, seed=seed, density=density)
        cfg = GameConfig(gen_limit=200)
        got = engine.simulate(g, cfg, kernel="packed")
        want = oracle.run(g, cfg)
        assert got.generations == want.generations == exit_gen, (seed, density)
        assert np.array_equal(got.grid, want.grid), (seed, density)


class TestCudaBlockTermination:
    """Pins the blocked CUDA-convention loop (engine._simulate_cuda_block):
    both exit kinds at varied offsets within the 16-generation vote block,
    including the empty-exit recovery replay (break-before-swap keeps the
    last non-empty generation, src/game_cuda.cu:259-268)."""

    @pytest.mark.parametrize("gen_limit", [1, 15, 16, 17, 31, 33, 48])
    def test_bound_straddles_blocks(self, gen_limit):
        g = text_grid.generate(64, 64, seed=5)  # soup: no early exit
        cfg = GameConfig(gen_limit=gen_limit, convention=Convention.CUDA)
        got = engine.simulate(g, cfg, kernel="packed")
        want = oracle.run(g, cfg)
        assert got.generations == want.generations == gen_limit
        assert np.array_equal(got.grid, want.grid)

    # Seeds chosen by oracle search: empty exits at in-block iterations
    # 0,1,3,5,7,9,12,13 (each replays that many recovery generations; seed
    # 166 exits mid-run so the replay starts from a non-initial block) plus
    # similarity exits at several offsets.
    @pytest.mark.parametrize(
        "seed,density,exit_gen",
        [
            (2, 0.04, 0), (0, 0.04, 1), (101, 0.04, 3), (40, 0.04, 5),
            (189, 0.04, 7), (142, 0.08, 9), (16, 0.06, 12), (210, 0.06, 13),
            (166, 0.06, 72),  # empty exits
            (91, 0.04, 17), (177, 0.08, 131), (27, 0.04, 5), (200, 0.18, 176),
        ],
    )
    def test_early_exits_at_varied_block_offsets(self, seed, density, exit_gen):
        g = text_grid.generate(32, 32, seed=seed, density=density)
        cfg = GameConfig(gen_limit=200, convention=Convention.CUDA)
        got = engine.simulate(g, cfg, kernel="packed")
        want = oracle.run(g, cfg)
        assert got.generations == want.generations == exit_gen, (seed, density)
        assert np.array_equal(got.grid, want.grid), (seed, density)

    def test_empty_exit_recovery_on_mesh(self):
        # The recovery replay runs per-shard under shard_map: the cond
        # predicate is psum-uniform, so every shard takes the same branch.
        g = text_grid.generate(64, 32, seed=72, density=0.03)  # dies at gen 4
        cfg = GameConfig(gen_limit=200, convention=Convention.CUDA)
        got = engine.simulate(g, cfg, mesh=make_mesh(2, 2), kernel="packed")
        want = oracle.run(g, cfg)
        assert got.generations == want.generations == 4
        assert got.grid.any()  # last non-empty generation, not the empty one
        assert np.array_equal(got.grid, want.grid)


def test_runner_cache_equal_meshes():
    # Mesh defines __eq__/__hash__ over the device grid + axis names, so
    # make_runner's lru_cache is keyed by value, not identity — a long-lived
    # server constructing its mesh per request compiles once.
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    m1 = Mesh(devs, ("row", "col"))
    m2 = Mesh(devs.copy(), ("row", "col"))
    assert m1 == m2 and hash(m1) == hash(m2)
    r1 = engine.make_runner((64, 64), GameConfig(), m1, "lax")
    r2 = engine.make_runner((64, 64), GameConfig(), m2, "lax")
    assert r1 is r2


class TestCompileFailureFallback:
    """The auto/packed lanes must survive a kernel that fails to compile.

    The packed VMEM caps are v5e-empirical; on another TPU generation a shape
    inside the caps can Mosaic-OOM at the runner's first (lazy) compile. The
    reference never dies on a supported shape (src/game.c:224-245), so the
    engine demotes packed -> packed-jnp -> lax with a stderr warning instead
    of crashing. Simulated here by making the packed step raise at trace time
    — same surface as a Mosaic compile error (first runner call).
    """

    def _boom_packed(self, monkeypatch, jnp_ok: bool):
        from gol_tpu.ops import stencil_packed

        orig_step = stencil_packed.packed_step
        orig_multi = stencil_packed.packed_step_multi

        def step(cur, topo, *, force_jnp=False):
            if not (jnp_ok and force_jnp):
                raise RuntimeError("simulated Mosaic compile OOM")
            return orig_step(cur, topo, force_jnp=True)

        def multi(cur, topo, *, force_jnp=False):
            if not (jnp_ok and force_jnp):
                raise RuntimeError("simulated Mosaic compile OOM")
            return orig_multi(cur, topo, force_jnp=True)

        monkeypatch.setattr(stencil_packed, "packed_step", step)
        monkeypatch.setattr(stencil_packed, "packed_step_multi", multi)

    def test_auto_demotes_to_lax(self, monkeypatch, caplog):
        # Both packed flavors fail -> the auto lane lands on lax and the run
        # still matches the oracle; each demotion logs a warning (the CLI
        # routes the gol_tpu logger to stderr).
        self._boom_packed(monkeypatch, jnp_ok=False)
        runner = engine._build_runner(
            (64, 64), GameConfig(gen_limit=20), None, "auto",
            segmented=False, packed_state=False,
        )
        assert runner.kernel_name == "packed"
        g = text_grid.generate(64, 64, seed=11)
        final, gen = runner(engine.put_grid(g))
        assert runner.kernel_name == "lax"
        want = oracle.run(g, GameConfig(gen_limit=20))
        assert int(gen) == want.generations
        assert np.array_equal(np.asarray(final), want.grid)
        assert "falling back to 'packed-jnp'" in caplog.text
        assert "falling back to 'lax'" in caplog.text

    def test_packed_state_demotes_to_jnp_network(self, monkeypatch, caplog):
        # The packed-state lane carries word state, so its ladder stops at
        # the jnp adder network — identical math, no Pallas.
        from gol_tpu.ops import packed_math

        self._boom_packed(monkeypatch, jnp_ok=True)
        runner = engine._build_runner(
            (64, 64), GameConfig(gen_limit=20), None, "packed",
            segmented=False, packed_state=True,
        )
        g = text_grid.generate(64, 64, seed=12)
        final, gen = runner(packed_math.encode(g))
        assert runner.kernel_name == "packed-jnp"
        want = oracle.run(g, GameConfig(gen_limit=20))
        assert int(gen) == want.generations
        assert np.array_equal(packed_math.decode(np.asarray(final)), want.grid)
        assert "falling back to 'packed-jnp'" in caplog.text

    def test_auto_demotes_on_mesh(self, monkeypatch):
        # Distributed demotion: the ladder rebuilds the whole shard_map
        # program per entry, and the lax landing stays oracle-exact.
        self._boom_packed(monkeypatch, jnp_ok=False)
        mesh = make_mesh(2, 2)
        runner = engine._build_runner(
            (64, 64), GameConfig(gen_limit=12), mesh, "auto",
            segmented=False, packed_state=False,
        )
        g = text_grid.generate(64, 64, seed=13)
        final, gen = runner(engine.put_grid(g, mesh))
        assert runner.kernel_name == "lax"
        want = oracle.run(g, GameConfig(gen_limit=12))
        assert int(gen) == want.generations
        assert np.array_equal(np.asarray(final), want.grid)

    def test_aot_compile_demotes(self, monkeypatch, caplog):
        # The CLI compiles before its timer (engine.compile_runner); the
        # ladder must demote at AOT-compile time too, not just at first call.
        self._boom_packed(monkeypatch, jnp_ok=False)
        runner = engine._build_runner(
            (64, 64), GameConfig(gen_limit=20), None, "auto",
            segmented=False, packed_state=False,
        )
        g = text_grid.generate(64, 64, seed=16)
        compiled = engine.compile_runner(runner, engine.put_grid(g))
        assert runner.kernel_name == "lax"
        final, gen = compiled(engine.put_grid(g))
        want = oracle.run(g, GameConfig(gen_limit=20))
        assert int(gen) == want.generations
        assert np.array_equal(np.asarray(final), want.grid)
        assert "falling back to 'lax'" in caplog.text

    def test_non_compile_errors_do_not_demote(self, monkeypatch):
        # Only compile-shaped failures (Mosaic/VMEM/OOM) may demote; a user
        # error raised at trace time must propagate from the chosen kernel,
        # not silently land on lax with the root cause buried in stderr.
        from gol_tpu.ops import stencil_packed

        def boom(cur, topo, *, force_jnp=False):
            raise ValueError("width must be a multiple of 32 (user error)")

        monkeypatch.setattr(stencil_packed, "packed_step", boom)
        monkeypatch.setattr(stencil_packed, "packed_step_multi", boom)
        runner = engine._build_runner(
            (64, 64), GameConfig(gen_limit=5), None, "auto",
            segmented=False, packed_state=False,
        )
        g = text_grid.generate(64, 64, seed=15)
        with pytest.raises(ValueError, match="user error"):
            runner(engine.put_grid(g))
        assert runner.kernel_name == "packed"  # never demoted

    def test_explicit_kernel_stays_strict(self, monkeypatch):
        # An explicitly named unpacked kernel must NOT silently demote — that
        # would mislabel benchmark numbers. The failure propagates.
        self._boom_packed(monkeypatch, jnp_ok=False)
        runner = engine._build_runner(
            (64, 64), GameConfig(gen_limit=5), None, "packed",
            segmented=False, packed_state=False,
        )
        g = text_grid.generate(64, 64, seed=14)
        with pytest.raises(RuntimeError, match="simulated Mosaic"):
            runner(engine.put_grid(g))


# Verbatim error text captured from REAL failures on the v5e attach tunnel
# (tools/probe_vmem_r4.py; full copies in benchmarks/vmem_probe_r4.json
# error_samples_full). The classifier is pinned against what the runtime
# actually says, not what we guessed it says (VERDICT r3 weak #4): a JAX /
# Mosaic release that rewords these turns a demotable compile failure back
# into a crash, and this test is what catches it.
_REAL_VMEM_COMPILE_ERROR = (
    "INTERNAL: http://127.0.0.1:8103/remote_compile: HTTP 500: "
    "tpu_compile_helper subprocess exit code 1\n"
    "[helper log elided — full text in benchmarks/vmem_probe_r4.json]\n"
    "compile: Internal: AOT PJRT error: Ran out of memory in memory space "
    "vmem while allocating on stack for %_step_t.1 = (u32[1024,7680]"
    "{1,0:T(8,128)}, s32[1,8]{1,0:T(1,128)}, s32[1,8]{1,0:T(1,128)}) "
    'custom-call(%words.1, %words.1, %words.1), custom_call_target='
    '"tpu_custom_call". Scoped allocation with size 16.57M and limit '
    "16.00M exceeded scoped vmem limit by 580.0K. It should not be "
    "possible to run out of scoped vmem -  see "
    "go/compile-time-vmem-oom#kernel-vmem-stack-oom for more information."
)
_REAL_HBM_OOM_ERROR = (
    "INTERNAL: http://127.0.0.1:8113/remote_compile: HTTP 500: "
    "tpu_compile_helper subprocess exit code 1\n"
    "[helper log elided]\n"
    "compile: Internal: AOT PJRT error: XLA:TPU compile permanent error. "
    "Ran out of memory in memory space hbm. Used 20.00G of 15.75G hbm. "
    "Exceeded hbm capacity by 4.25G."
)
# The same tunnel wrapper when the helper dies WITHOUT an embedded compile
# message (observed truncation shape: log lines only) — the remote_compile
# marks are what classify it.
_REAL_TUNNEL_WRAPPER_ONLY = (
    "INTERNAL: http://127.0.0.1:8083/remote_compile: HTTP 500: "
    "tpu_compile_helper subprocess exit code 1\n"
    "compile-helper: landlock not enforced on this kernel; continuing\n"
    "tpu-compile helper: compiling via TpuAotCompiler (chipless)"
)


def test_compile_failure_real_error_text():
    import jax

    for text in (_REAL_VMEM_COMPILE_ERROR, _REAL_HBM_OOM_ERROR,
                 _REAL_TUNNEL_WRAPPER_ONLY):
        assert engine._is_compile_failure(jax.errors.JaxRuntimeError(text)), text[:80]
        # The same text in a bare RuntimeError (how a different wrapper
        # might surface it) still classifies via the substring family.
        assert engine._is_compile_failure(RuntimeError(text)), text[:80]
    # Typed path: a status-coded RESOURCE_EXHAUSTED with no known substring.
    assert engine._is_compile_failure(
        jax.errors.JaxRuntimeError("RESOURCE_EXHAUSTED: allocation failed")
    )
    # Non-compile failures must NOT demote: user errors and unrelated
    # runtime statuses.
    assert not engine._is_compile_failure(
        ValueError("width must be a multiple of 32")
    )
    assert not engine._is_compile_failure(
        jax.errors.JaxRuntimeError(
            "INVALID_ARGUMENT: Argument does not match host shape"
        )
    )
    assert not engine._is_compile_failure(
        jax.errors.JaxRuntimeError("FAILED_PRECONDITION: device in bad state")
    )


def test_tunnel_wrapper_only_classification():
    import jax

    # Only the helper-wrapper marks, no embedded compile evidence: eligible
    # for the one-shot retry.
    assert engine._is_tunnel_wrapper_only(
        jax.errors.JaxRuntimeError(_REAL_TUNNEL_WRAPPER_ONLY))
    # Embedded VMEM/OOM text or a status code: a real compile failure, no
    # retry — demote immediately.
    assert not engine._is_tunnel_wrapper_only(
        jax.errors.JaxRuntimeError(_REAL_VMEM_COMPILE_ERROR))
    assert not engine._is_tunnel_wrapper_only(
        jax.errors.JaxRuntimeError(_REAL_HBM_OOM_ERROR))
    assert not engine._is_tunnel_wrapper_only(
        jax.errors.JaxRuntimeError("RESOURCE_EXHAUSTED: remote_compile"))
    assert not engine._is_tunnel_wrapper_only(ValueError("user error"))


def test_tunnel_outage_retries_once_before_demoting(monkeypatch, caplog):
    """A compile failure carrying ONLY the attach-tunnel wrapper marks may
    be a transient helper outage (advisor r4): the ladder retries the same
    entry once. If the retry succeeds the run stays on the fast kernel; a
    second failure demotes as before."""
    from gol_tpu.ops import stencil_packed

    orig_multi = stencil_packed.packed_step_multi
    orig_step = stencil_packed.packed_step
    failures = {"n": 0}

    def flaky_multi(cur, topo, *, force_jnp=False, force_interp=False):
        if not force_jnp and failures["n"] < 1:
            failures["n"] += 1
            raise RuntimeError(_REAL_TUNNEL_WRAPPER_ONLY)
        return orig_multi(cur, topo, force_jnp=force_jnp,
                          force_interp=force_interp)

    monkeypatch.setattr(stencil_packed, "packed_step_multi", flaky_multi)
    runner = engine._build_runner(
        (64, 64), GameConfig(gen_limit=20), None, "auto",
        segmented=False, packed_state=False,
    )
    g = text_grid.generate(64, 64, seed=21)
    final, gen = runner(engine.put_grid(g))
    # One transient outage: retried, stayed on the packed kernel.
    assert runner.kernel_name == "packed"
    want = oracle.run(g, GameConfig(gen_limit=20))
    assert int(gen) == want.generations
    assert np.array_equal(np.asarray(final), want.grid)
    assert "retrying once before demoting" in caplog.text
    assert "falling back" not in caplog.text
    caplog.clear()

    # Persistent outage: the retry fails too -> demotes down the ladder.
    failures["n"] = -1000  # always raise for the non-jnp route

    def dead_multi(cur, topo, *, force_jnp=False, force_interp=False):
        if not force_jnp:
            raise RuntimeError(_REAL_TUNNEL_WRAPPER_ONLY)
        return orig_multi(cur, topo, force_jnp=True)

    def dead_step(cur, topo, *, force_jnp=False, force_interp=False):
        if not force_jnp:
            raise RuntimeError(_REAL_TUNNEL_WRAPPER_ONLY)
        return orig_step(cur, topo, force_jnp=True)

    monkeypatch.setattr(stencil_packed, "packed_step_multi", dead_multi)
    monkeypatch.setattr(stencil_packed, "packed_step", dead_step)
    runner2 = engine._build_runner(
        (64, 96), GameConfig(gen_limit=20), None, "auto",
        segmented=False, packed_state=False,
    )
    g2 = text_grid.generate(64, 96, seed=22)
    final2, gen2 = runner2(engine.put_grid(g2))
    assert runner2.kernel_name == "packed-jnp"
    want2 = oracle.run(g2, GameConfig(gen_limit=20))
    assert int(gen2) == want2.generations
    assert np.array_equal(np.asarray(final2), want2.grid)
    assert "retrying once before demoting" in caplog.text
    assert "falling back to 'packed-jnp'" in caplog.text


def test_no_collective_under_conditional():
    # A psum under a data-dependent lax.cond deadlocks backends that cannot
    # prove the predicate SPMD-uniform. The engine's similarity vote keeps the
    # O(grid) compare behind the cond but runs the collective unconditionally
    # on the masked flag (engine._similarity_vote) — matching the reference's
    # unconditional every-3rd-gen similarity_all
    # (src/game_mpi_collective.c:353-361). Pin it by walking the lowered
    # StableHLO: no all_reduce may appear inside an if/case region.
    mesh = make_mesh(2, 2)
    runner = engine._build_runner(
        (16, 16), GameConfig(gen_limit=10), mesh, "lax",
        segmented=False, packed_state=False,
    )
    grid = engine.put_grid(np.zeros((16, 16), np.uint8), mesh)
    txt = runner.lower(grid).as_text()
    assert txt.count("all_reduce") > 0  # the votes are still collectives
    region_stack, offenders = [], []
    for line in txt.splitlines():
        if "stablehlo.if" in line or "stablehlo.case" in line:
            region_stack.append(line.count("{") - line.count("}"))
        elif region_stack:
            region_stack[-1] += line.count("{") - line.count("}")
            if "all_reduce" in line:
                offenders.append(line.strip())
            if region_stack[-1] <= 0:
                region_stack.pop()
    assert offenders == []
