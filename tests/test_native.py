"""Native codec + packed I/O tests.

The C codec must agree byte-for-byte with the numpy fallback and with the
text-grid contract; the packed I/O lane must round-trip files identically to
the byte-level sharded I/O.
"""

import shutil

import numpy as np
import pytest

from gol_tpu import cli, native, oracle
from gol_tpu.config import GameConfig
from gol_tpu.io import packed_io, text_grid
from gol_tpu.ops import packed_math
from gol_tpu.parallel.mesh import make_mesh

import jax.numpy as jnp


@pytest.mark.skipif(
    not any(shutil.which(cc) for cc in ("cc", "gcc", "clang")),
    reason="no C toolchain on PATH (the codec falls back to numpy)",
)
def test_native_codec_builds():
    # Wherever a C toolchain exists, the codec must actually build.
    assert native.available()


def test_pack_text_matches_encode():
    rng = np.random.default_rng(1)
    g = rng.integers(0, 2, size=(16, 96), dtype=np.uint8)
    text = g + ord("0")
    words = native.pack_text(text, 96)
    expect = np.asarray(packed_math.encode(jnp.asarray(g)))
    np.testing.assert_array_equal(words, expect)


def test_pack_text_strict_one(monkeypatch):
    """Only '1' is alive — '3' (odd byte) must pack as dead, like text_grid.
    Checked on both the native path and the numpy fallback."""
    text = np.full((1, 32), ord("0"), np.uint8)
    text[0, 0] = ord("1")
    text[0, 1] = ord("3")
    assert native.pack_text(text, 32)[0, 0] == 1  # native: just bit 0
    monkeypatch.setattr(native, "_load", lambda: None)
    assert native.pack_text(text, 32)[0, 0] == 1  # numpy fallback too


def test_pack_text_strided_window():
    """Pack through a memmap-style strided view (the newline-column layout)."""
    rng = np.random.default_rng(2)
    g = rng.integers(0, 2, size=(8, 64), dtype=np.uint8)
    raw = np.full((8, 65), ord("\n"), np.uint8)
    raw[:, :64] = g + ord("0")
    words = native.pack_text(raw, 64)  # full stride incl newline col
    expect = np.asarray(packed_math.encode(jnp.asarray(g)))
    np.testing.assert_array_equal(words, expect)


def test_unpack_text_roundtrip():
    rng = np.random.default_rng(3)
    g = rng.integers(0, 2, size=(8, 64), dtype=np.uint8)
    words = np.asarray(packed_math.encode(jnp.asarray(g)))
    out = np.zeros((8, 65), np.uint8)
    native.unpack_text(words, out, 64, True)
    np.testing.assert_array_equal(out[:, :64], g + ord("0"))
    assert (out[:, 64] == ord("\n")).all()


def test_packed_file_roundtrip(tmp_path):
    rng = np.random.default_rng(4)
    g = rng.integers(0, 2, size=(32, 128), dtype=np.uint8)
    path = tmp_path / "grid.txt"
    text_grid.write_grid(str(path), g)
    words = packed_io.read_packed(str(path), 128, 32)
    np.testing.assert_array_equal(
        np.asarray(packed_math.decode(jnp.asarray(words))), g
    )
    out = tmp_path / "out.txt"
    packed_io.write_packed(str(out), words, 128)
    assert out.read_bytes() == path.read_bytes()


def test_packed_file_roundtrip_sharded(tmp_path):
    mesh = make_mesh(2, 4)
    rng = np.random.default_rng(5)
    g = rng.integers(0, 2, size=(64, 256), dtype=np.uint8)
    path = tmp_path / "grid.txt"
    text_grid.write_grid(str(path), g)
    words = packed_io.read_packed(str(path), 256, 64, mesh)
    assert words.shape == (64, 8)
    out = tmp_path / "out.txt"
    packed_io.write_packed(str(out), words, 256)
    assert out.read_bytes() == path.read_bytes()


@pytest.mark.parametrize("pipeline", [False, True])
def test_packed_file_roundtrip_chunked(tmp_path, monkeypatch, pipeline):
    """Force the streaming chunk paths (normally >64/128 MB) on a small grid,
    both upload strategies (single-transfer and the pipelined per-chunk
    device_put + concatenate an accelerator backend would take)."""
    monkeypatch.setattr(packed_io, "_READ_CHUNK_BYTES", 5 * 129)  # ~5 rows/chunk
    monkeypatch.setattr(packed_io, "_WRITE_CHUNK_BYTES", 3 * 16)  # 3 rows/chunk
    monkeypatch.setattr(packed_io, "_FORCE_READ_PIPELINE", pipeline)
    rng = np.random.default_rng(9)
    g = rng.integers(0, 2, size=(37, 128), dtype=np.uint8)
    path = tmp_path / "grid.txt"
    text_grid.write_grid(str(path), g)
    words = packed_io.read_packed(str(path), 128, 37)
    np.testing.assert_array_equal(
        np.asarray(packed_math.decode(jnp.asarray(words))), g
    )
    out = tmp_path / "out.txt"
    packed_io.write_packed(str(out), words, 128)
    assert out.read_bytes() == path.read_bytes()


def test_packed_io_width_validation(tmp_path):
    with pytest.raises(ValueError, match="divisible by 32"):
        packed_io.read_packed(str(tmp_path / "x"), 48, 16, None)


def test_cli_packed_io_end_to_end(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rng = np.random.default_rng(6)
    g = rng.integers(0, 2, size=(64, 64), dtype=np.uint8)
    text_grid.write_grid("in.txt", g)
    rc = cli.main(
        ["64", "64", "in.txt", "--variant", "game", "--gen-limit", "25", "--packed-io"]
    )
    assert rc == 0
    expect = oracle.run(g, GameConfig(gen_limit=25))
    got = text_grid.read_grid("game_output.out", 64, 64)
    np.testing.assert_array_equal(got, expect.grid)
