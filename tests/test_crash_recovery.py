"""Crash-recovery harness (ISSUE 1 acceptance): fault injection kills a
checkpointed CLI run at every checkpoint boundary — and fails a write
mid-checkpoint — and ``--auto-resume`` must restore to a final output file
byte-identical to the uninterrupted run's, reporting the same generation
count. A crash must never leave the checkpoint dir without a readable prior
state.

Runs drive ``cli.main`` in-process with ``kill_mode=exception`` faults:
``InjectedCrash`` derives from BaseException, so — like SIGKILL — nothing
between the injection point and this harness gets to clean up.
"""

import json
import os

import pytest

from gol_tpu import cli
from gol_tpu.io import text_grid, ts_store
from gol_tpu.resilience import faults
from gol_tpu.resilience.faults import InjectedCrash


@pytest.fixture(autouse=True)
def _disarmed():
    faults.clear()
    yield
    faults.clear()


GEN_LIMIT = 12
EVERY = 3
BOUNDARIES = (3, 6, 9)  # generation 12 is the (uncheckpointed) final state


def _run(capsys, args):
    capsys.readouterr()  # drain anything a previous run printed
    rc = cli.main(args)
    return rc, capsys.readouterr()


def _gens_line(out):
    return [l for l in out.splitlines() if l.startswith("Generations")]


def _args(infile, out, ckdir, *extra):
    return [
        "16", "16", infile,
        "--variant", "game",
        "--gen-limit", str(GEN_LIMIT),
        "--checkpoint-every", str(EVERY),
        "--checkpoint-dir", str(ckdir),
        "--output", str(out),
        *extra,
    ]


@pytest.fixture
def grid16(tmp_path):
    p = tmp_path / "in.txt"
    text_grid.write_grid(str(p), text_grid.generate(16, 16, seed=77))
    return str(p)


@pytest.fixture
def reference(tmp_path, grid16, capsys):
    """Uninterrupted, checkpoint-free run: the byte-for-byte target."""
    out = tmp_path / "ref.out"
    rc, cap = _run(capsys, [
        "16", "16", grid16, "--variant", "game",
        "--gen-limit", str(GEN_LIMIT), "--output", str(out),
    ])
    assert rc == 0
    return out.read_bytes(), _gens_line(cap.out)


def _assert_prior_state_readable(ckdir):
    """Every committed manifest must point at an existing payload — a crash
    window may orphan payloads (invisible) but never dangle a manifest."""
    if not os.path.isdir(ckdir):
        return
    for name in os.listdir(ckdir):
        if name.endswith(".manifest.json"):
            with open(os.path.join(ckdir, name)) as f:
                manifest = json.load(f)
            payload = os.path.join(ckdir, manifest["payload"])
            assert os.path.exists(payload), (
                f"manifest {name} dangles: {manifest['payload']} missing"
            )


@pytest.mark.parametrize("kill_at", BOUNDARIES)
def test_kill_at_every_boundary_then_auto_resume(
    tmp_path, grid16, reference, capsys, kill_at
):
    ref_bytes, ref_gens = reference
    ckdir = tmp_path / f"ck{kill_at}"
    out = tmp_path / f"out{kill_at}.out"

    with pytest.raises(InjectedCrash):
        cli.main(_args(grid16, out, ckdir,
                       "--fault-plan", f"kill_at_gen={kill_at}"))
    _assert_prior_state_readable(str(ckdir))
    if kill_at > EVERY:
        # Boundaries before the kill committed checkpoints; the newest must
        # be the boundary just before the crash.
        manifests = sorted(
            n for n in os.listdir(ckdir) if n.endswith(".manifest.json")
        )
        assert manifests[-1] == f"ckpt-{kill_at - EVERY:08d}.manifest.json"
    assert not out.exists()  # the crash preceded the final write

    rc, cap = _run(capsys, _args(grid16, out, ckdir, "--auto-resume"))
    assert rc == 0
    assert out.read_bytes() == ref_bytes
    assert _gens_line(cap.out) == ref_gens


def test_env_var_fault_plan_crosses_into_run(
    tmp_path, grid16, reference, capsys, monkeypatch
):
    """GOL_FAULTS drives the same injection without argv (the subprocess
    harness's channel), and the next env-clean run is fault-free."""
    ref_bytes, _ = reference
    ckdir, out = tmp_path / "ck", tmp_path / "out.out"
    monkeypatch.setenv("GOL_FAULTS", "kill_at_gen=6")
    with pytest.raises(InjectedCrash):
        cli.main(_args(grid16, out, ckdir))
    monkeypatch.delenv("GOL_FAULTS")
    rc, _ = _run(capsys, _args(grid16, out, ckdir, "--auto-resume"))
    assert rc == 0
    assert out.read_bytes() == ref_bytes


def test_midwrite_failure_keeps_prior_and_resumes(
    tmp_path, grid16, reference, capsys
):
    """Failing the 2nd checkpoint write (generation 6) mid-checkpoint: the
    run aborts, generation 3 stays restorable, auto-resume completes to the
    identical output."""
    ref_bytes, ref_gens = reference
    ckdir, out = tmp_path / "ck", tmp_path / "out.out"
    rc, cap = _run(capsys, _args(grid16, out, ckdir,
                                 "--fault-plan", "payload_write_fail=2"))
    assert rc == 1  # the injected OSError aborts the run loudly
    assert "injected" in cap.err
    _assert_prior_state_readable(str(ckdir))
    names = os.listdir(ckdir)
    assert "ckpt-00000003.manifest.json" in names  # prior state intact
    assert "ckpt-00000006.manifest.json" not in names  # torn one invisible

    rc, cap = _run(capsys, _args(grid16, out, ckdir, "--auto-resume"))
    assert rc == 0
    assert out.read_bytes() == ref_bytes
    assert _gens_line(cap.out) == ref_gens


def test_auto_resume_with_empty_dir_runs_from_scratch(
    tmp_path, grid16, reference, capsys
):
    ref_bytes, ref_gens = reference
    out = tmp_path / "out.out"
    rc, cap = _run(capsys, _args(grid16, out, tmp_path / "ck", "--auto-resume"))
    assert rc == 0
    assert out.read_bytes() == ref_bytes
    assert _gens_line(cap.out) == ref_gens


def test_checkpointed_run_is_bit_exact_without_crashes(
    tmp_path, grid16, reference, capsys
):
    """Checkpointing must not perturb the run it protects."""
    ref_bytes, ref_gens = reference
    out = tmp_path / "out.out"
    rc, cap = _run(capsys, _args(grid16, out, tmp_path / "ck"))
    assert rc == 0
    assert out.read_bytes() == ref_bytes
    assert _gens_line(cap.out) == ref_gens


def test_auto_resume_respects_reduced_gen_limit(tmp_path, grid16, capsys):
    """Rerunning with a smaller --gen-limit must not resurface a checkpoint
    past the limit (the --resume-gen validator's guarantee): the run resumes
    from the newest checkpoint at or below it — an exact prefix — or starts
    fresh, and either way matches the uninterrupted shorter run."""
    ckdir = tmp_path / "ck"
    out = tmp_path / "out.out"
    rc, _ = _run(capsys, _args(grid16, out, ckdir))  # checkpoints 6 and 9 kept
    assert rc == 0
    for limit, expect_resume in ((8, True), (5, False)):
        ref = tmp_path / f"ref{limit}.out"
        rc, cap = _run(capsys, [
            "16", "16", grid16, "--variant", "game",
            "--gen-limit", str(limit), "--output", str(ref),
        ])
        assert rc == 0
        ref_gens = _gens_line(cap.out)
        short_out = tmp_path / f"short{limit}.out"
        rc, cap = _run(capsys, [
            "16", "16", grid16, "--variant", "game",
            "--gen-limit", str(limit),
            "--checkpoint-every", str(EVERY), "--checkpoint-dir", str(ckdir),
            "--auto-resume", "--output", str(short_out),
        ])
        assert rc == 0
        assert short_out.read_bytes() == ref.read_bytes()
        assert _gens_line(cap.out) == ref_gens
        assert ("restored checkpoint" in cap.err) == expect_resume


def test_stale_dir_from_different_input_never_restored(tmp_path, reference,
                                                       capsys):
    """A checkpoint dir reused across inputs: run B must never resume from
    run A's state (manifest fingerprints mismatch), and must still produce
    its own correct output."""
    ref_bytes, ref_gens = reference
    a_in = tmp_path / "a.txt"
    text_grid.write_grid(str(a_in), text_grid.generate(16, 16, seed=99))
    ckdir = tmp_path / "ck"
    rc, _ = _run(capsys, _args(str(a_in), tmp_path / "a.out", ckdir))
    assert rc == 0  # run A fills the dir with its checkpoints

    b_in = tmp_path / "in.txt"  # the `reference` fixture's input (seed 77)
    out = tmp_path / "b.out"
    rc, cap = _run(capsys, _args(str(b_in), out, ckdir, "--auto-resume"))
    assert rc == 0
    assert "restored checkpoint" not in cap.err  # A's state was refused
    assert out.read_bytes() == ref_bytes
    assert _gens_line(cap.out) == ref_gens


def test_similarity_exit_resumes_to_same_generation(tmp_path, capsys):
    """Crash-resume across a similarity early-exit: the resumed run must
    report the same early-exit generation (23), not re-count."""
    infile = tmp_path / "sim.txt"
    text_grid.write_grid(str(infile), text_grid.generate(16, 16, seed=26,
                                                         density=0.25))
    base = ["16", "16", str(infile), "--variant", "game", "--gen-limit", "40"]
    out_ref = tmp_path / "ref.out"
    rc, cap = _run(capsys, [*base, "--output", str(out_ref)])
    assert rc == 0
    ref_gens = _gens_line(cap.out)
    assert ref_gens and ref_gens[0].split("\t")[1] == "23"  # scenario sanity

    ckdir, out = tmp_path / "ck", tmp_path / "out.out"
    ck = ["--checkpoint-every", "5", "--checkpoint-dir", str(ckdir),
          "--output", str(out)]
    with pytest.raises(InjectedCrash):
        cli.main([*base, *ck, "--fault-plan", "kill_at_gen=20"])
    rc, cap = _run(capsys, [*base, *ck, "--auto-resume"])
    assert rc == 0
    assert out.read_bytes() == out_ref.read_bytes()
    assert _gens_line(cap.out) == ref_gens


def test_packed_io_lane_kill_and_resume(tmp_path, capsys):
    """The packed lane's checkpoint codec (zarr when tensorstore is present,
    packed text otherwise) through the same kill-and-resume cycle."""
    infile = tmp_path / "in.txt"
    text_grid.write_grid(str(infile), text_grid.generate(64, 64, seed=21,
                                                         density=0.35))
    base = ["64", "64", str(infile), "--variant", "tpu", "--packed-io",
            "--gen-limit", str(GEN_LIMIT)]
    out_ref = tmp_path / "ref.out"
    rc, cap = _run(capsys, [*base, "--output", str(out_ref)])
    assert rc == 0
    ref_gens = _gens_line(cap.out)

    ckdir, out = tmp_path / "ck", tmp_path / "out.out"
    ck = ["--checkpoint-every", str(EVERY), "--checkpoint-dir", str(ckdir),
          "--output", str(out)]
    with pytest.raises(InjectedCrash):
        cli.main([*base, *ck, "--fault-plan", "kill_at_gen=6"])
    _assert_prior_state_readable(str(ckdir))
    rc, cap = _run(capsys, [*base, *ck, "--auto-resume"])
    assert rc == 0
    assert out.read_bytes() == out_ref.read_bytes()
    assert _gens_line(cap.out) == ref_gens


@pytest.mark.skipif(not ts_store.HAVE_TENSORSTORE,
                    reason="tensorstore not installed")
def test_packed_io_hard_shard_write_failure_mid_checkpoint(tmp_path, capsys):
    """A hard tensorstore shard-write failure inside the 2nd checkpoint's
    payload: the run aborts naming the shard, the 1st checkpoint survives,
    auto-resume restores byte-identically."""
    infile = tmp_path / "in.txt"
    text_grid.write_grid(str(infile), text_grid.generate(64, 64, seed=21,
                                                         density=0.35))
    base = ["64", "64", str(infile), "--variant", "tpu", "--packed-io",
            "--gen-limit", str(GEN_LIMIT)]
    out_ref = tmp_path / "ref.out"
    rc, _ = _run(capsys, [*base, "--output", str(out_ref)])
    assert rc == 0

    ckdir, out = tmp_path / "ck", tmp_path / "out.out"
    ck = ["--checkpoint-every", str(EVERY), "--checkpoint-dir", str(ckdir),
          "--output", str(out)]
    # The first checkpoint writes one shard per device; failing write
    # devices+1 lands inside the SECOND checkpoint's payload.
    import jax

    nth = jax.local_device_count() + 1
    rc, cap = _run(capsys, [*base, *ck, "--fault-plan",
                            f"ts_write_fail={nth}"])
    assert rc == 1
    assert "shard indices" in cap.err
    _assert_prior_state_readable(str(ckdir))
    assert any(n.endswith(".manifest.json") for n in os.listdir(ckdir))

    rc, _ = _run(capsys, [*base, *ck, "--auto-resume"])
    assert rc == 0
    assert out.read_bytes() == out_ref.read_bytes()


def test_kill_during_async_write_then_auto_resume(tmp_path, grid16,
                                                  reference, capsys):
    """SIGKILL-equivalent crash while the ASYNC writer (the default
    checkpoint lane since the pipeline PR) is mid-payload-write: the torn
    payload must never become a visible checkpoint (its manifest commits
    only at the next boundary's deferred wait, which the crash precedes),
    the previous boundary's checkpoint — committed by THIS boundary's wait
    — survives, and auto-resume is byte-identical. This is the gen-limit
    exit path; the similarity-exit path is the test below."""
    ref_bytes, ref_gens = reference
    ckdir, out = tmp_path / "ck", tmp_path / "out.out"
    # Payload write #2 is generation 6's: at that moment the deferred wait
    # at boundary 6 has already committed generation 3.
    with pytest.raises(InjectedCrash):
        cli.main(_args(grid16, out, ckdir,
                       "--fault-plan", "kill_during_ckpt_write=2"))
    _assert_prior_state_readable(str(ckdir))
    manifests = sorted(
        n for n in os.listdir(ckdir) if n.endswith(".manifest.json"))
    assert manifests == ["ckpt-00000003.manifest.json"]
    assert not out.exists()

    rc, cap = _run(capsys, _args(grid16, out, ckdir, "--auto-resume"))
    assert rc == 0
    assert out.read_bytes() == ref_bytes
    assert _gens_line(cap.out) == ref_gens


def test_kill_during_async_write_similarity_exit_path(tmp_path, capsys):
    """Same mid-async-write kill on a run that ends in a similarity early
    exit (generation 23): the resumed run must report the identical exit
    generation and output — the other exit path of the acceptance."""
    infile = tmp_path / "sim.txt"
    text_grid.write_grid(str(infile), text_grid.generate(16, 16, seed=26,
                                                         density=0.25))
    base = ["16", "16", str(infile), "--variant", "game", "--gen-limit", "40"]
    out_ref = tmp_path / "ref.out"
    rc, cap = _run(capsys, [*base, "--output", str(out_ref)])
    assert rc == 0
    ref_gens = _gens_line(cap.out)
    assert ref_gens and ref_gens[0].split("\t")[1] == "23"  # scenario sanity

    ckdir, out = tmp_path / "ck", tmp_path / "out.out"
    ck = ["--checkpoint-every", "5", "--checkpoint-dir", str(ckdir),
          "--output", str(out)]
    # Payload write #3 is generation 15's; boundaries 5 and 10 committed.
    with pytest.raises(InjectedCrash):
        cli.main([*base, *ck, "--fault-plan", "kill_during_ckpt_write=3"])
    _assert_prior_state_readable(str(ckdir))
    names = os.listdir(ckdir)
    assert "ckpt-00000010.manifest.json" in names
    assert "ckpt-00000015.manifest.json" not in names

    rc, cap = _run(capsys, [*base, *ck, "--auto-resume"])
    assert rc == 0
    assert out.read_bytes() == out_ref.read_bytes()
    assert _gens_line(cap.out) == ref_gens


def test_kill_during_sync_write_matches_async_semantics(tmp_path, grid16,
                                                        reference, capsys):
    """The same fault on the --sync-checkpoints lane: the kill fires inside
    the foreground save, the in-progress checkpoint never commits, and
    resume is byte-identical — the two writers share one crash contract."""
    ref_bytes, ref_gens = reference
    ckdir, out = tmp_path / "ck", tmp_path / "out.out"
    with pytest.raises(InjectedCrash):
        cli.main(_args(grid16, out, ckdir, "--sync-checkpoints",
                       "--fault-plan", "kill_during_ckpt_write=2"))
    _assert_prior_state_readable(str(ckdir))
    names = os.listdir(ckdir)
    assert "ckpt-00000003.manifest.json" in names
    assert "ckpt-00000006.manifest.json" not in names

    rc, cap = _run(capsys, _args(grid16, out, ckdir, "--auto-resume"))
    assert rc == 0
    assert out.read_bytes() == ref_bytes
    assert _gens_line(cap.out) == ref_gens


def test_transient_faults_heal_without_aborting(tmp_path, grid16, reference,
                                                capsys):
    """Transient injected IO failures are retried under the unified policy:
    the run completes with no crash and the identical output."""
    if not ts_store.HAVE_TENSORSTORE:
        pytest.skip("tensorstore not installed")
    infile = tmp_path / "in.txt"
    text_grid.write_grid(str(infile), text_grid.generate(64, 64, seed=21,
                                                         density=0.35))
    base = ["64", "64", str(infile), "--variant", "tpu", "--packed-io",
            "--gen-limit", str(GEN_LIMIT)]
    out_ref = tmp_path / "ref.out"
    rc, _ = _run(capsys, [*base, "--output", str(out_ref)])
    assert rc == 0
    ckdir, out = tmp_path / "ck", tmp_path / "out.out"
    rc, _ = _run(capsys, [*base, "--checkpoint-every", str(EVERY),
                          "--checkpoint-dir", str(ckdir),
                          "--output", str(out), "--fault-plan",
                          "ts_write_fail=2,ts_write_error=transient,"
                          "ts_open_transient=1"])
    assert rc == 0
    assert out.read_bytes() == out_ref.read_bytes()


class TestFlagValidation:
    def _rc_err(self, capsys, args):
        capsys.readouterr()
        rc = cli.main(args)
        return rc, capsys.readouterr().err

    def test_dir_without_mode(self, tmp_path, grid16, capsys):
        rc, err = self._rc_err(capsys, [
            "16", "16", grid16, "--checkpoint-dir", str(tmp_path / "ck")])
        assert rc == 1 and "--checkpoint-every" in err

    def test_nonpositive_interval(self, tmp_path, grid16, capsys):
        rc, err = self._rc_err(capsys, [
            "16", "16", grid16, "--checkpoint-every", "0"])
        assert rc == 1 and "positive" in err

    def test_snapshot_every_conflicts(self, tmp_path, grid16, capsys):
        rc, err = self._rc_err(capsys, [
            "16", "16", grid16, "--checkpoint-every", "3",
            "--snapshot-every", "3"])
        assert rc == 1 and "snapshot" in err

    def test_auto_resume_conflicts_with_resume_gen(self, tmp_path, grid16,
                                                   capsys):
        rc, err = self._rc_err(capsys, [
            "16", "16", grid16, "--auto-resume", "--resume-gen", "5"])
        assert rc == 1 and "--resume-gen" in err

    def test_host_has_no_checkpoint_lane(self, tmp_path, grid16, capsys):
        rc, err = self._rc_err(capsys, [
            "16", "16", grid16, "--host", "--checkpoint-every", "3"])
        assert rc == 1 and "--host" in err

    def test_bad_fault_plan_is_loud(self, tmp_path, grid16, capsys):
        rc, err = self._rc_err(capsys, [
            "16", "16", grid16, "--fault-plan", "ts_write_fial=1"])
        assert rc == 1 and "unknown fault plan key" in err
