"""Semantics tests for the serial oracle — pins the reference's behavior.

The reference has no tests; its de-facto methodology is differential runs of
six programs on the same input (SURVEY.md §4). These known-pattern tests pin
the GoL semantics that methodology assumes: rule B3/S23, toroidal wrap, the
empty early-exit (src/game.c:177) and the similarity early-exit with its
generation accounting (src/game.c:181-189,202).
"""

import numpy as np
import pytest

from gol_tpu.config import Convention, GameConfig
from gol_tpu import oracle


def grid_from_strings(rows):
    return np.array([[1 if c == "1" else 0 for c in r] for r in rows], dtype=np.uint8)


class TestEvolve:
    def test_blinker_period_two(self):
        horiz = grid_from_strings(["00000", "00000", "01110", "00000", "00000"])
        vert = grid_from_strings(["00000", "00100", "00100", "00100", "00000"])
        assert np.array_equal(oracle.evolve(horiz), vert)
        assert np.array_equal(oracle.evolve(vert), horiz)

    def test_block_still_life(self):
        block = grid_from_strings(["0000", "0110", "0110", "0000"])
        assert np.array_equal(oracle.evolve(block), block)

    def test_all_dead_stays_dead(self):
        dead = np.zeros((6, 6), dtype=np.uint8)
        assert np.array_equal(oracle.evolve(dead), dead)

    def test_lone_cell_dies(self):
        g = np.zeros((5, 5), dtype=np.uint8)
        g[2, 2] = 1
        assert oracle.evolve(g).sum() == 0

    def test_birth_on_exactly_three(self):
        g = grid_from_strings(["00000", "01010", "00000", "00100", "00000"])
        # Cell (2,2) has exactly 3 neighbors -> born.
        assert oracle.evolve(g)[2, 2] == 1

    def test_toroidal_wrap_corners(self):
        # Three cells clustered across the corner torus seam form a neighborhood.
        g = np.zeros((6, 6), dtype=np.uint8)
        g[0, 0] = g[0, 5] = g[5, 0] = 1
        # Cell (5,5) touches all three via wrap -> born.
        assert oracle.evolve(g)[5, 5] == 1

    def test_glider_translates(self):
        glider = grid_from_strings(
            ["0100000", "0010000", "1110000", "0000000", "0000000", "0000000", "0000000"]
        )
        g = glider
        for _ in range(4):
            g = oracle.evolve(g)
        # After 4 generations a glider moves one cell down-right.
        assert np.array_equal(g, np.roll(glider, (1, 1), axis=(0, 1)))

    def test_glider_wraps_around_torus(self):
        glider = np.zeros((8, 8), dtype=np.uint8)
        glider[0, 1] = glider[1, 2] = glider[2, 0] = glider[2, 1] = glider[2, 2] = 1
        g = glider
        for _ in range(4 * 8):  # 8 diagonal steps of 1 cell = full wrap
            g = oracle.evolve(g)
        assert np.array_equal(g, glider)


class TestRunAccounting:
    def test_all_dead_zero_generations(self):
        # empty() is evaluated before the first generation (src/game.c:177).
        r = oracle.run(np.zeros((8, 8), dtype=np.uint8))
        assert r.generations == 0
        assert r.grid.sum() == 0

    def test_still_life_similarity_exit(self):
        # block: every generation equals the last; the check fires when
        # counter==SIMILARITY_FREQUENCY i.e. during generation 3, and the
        # reference reports generation-1 = 2 (src/game.c:183-188,202).
        block = grid_from_strings(["0000", "0110", "0110", "0000"])
        r = oracle.run(block)
        assert r.generations == 2
        assert np.array_equal(r.grid, block)

    def test_blinker_never_triggers_similarity(self):
        # Period-2: consecutive generations always differ -> runs to gen_limit.
        blinker = grid_from_strings(["00000", "00000", "01110", "00000", "00000"])
        cfg = GameConfig(gen_limit=10)
        r = oracle.run(blinker, cfg)
        assert r.generations == 10

    def test_gen_limit_inclusive(self):
        # while (gen <= GEN_LIMIT) runs exactly GEN_LIMIT generations
        # (src/game.c:177); glider on a big-enough torus never stabilizes.
        glider = np.zeros((16, 16), dtype=np.uint8)
        glider[0, 1] = glider[1, 2] = glider[2, 0] = glider[2, 1] = glider[2, 2] = 1
        cfg = GameConfig(gen_limit=7, check_similarity=False)
        r = oracle.run(glider, cfg)
        assert r.generations == 7

    def test_death_before_similarity_check(self):
        # A lone cell dies in generation 1; the empty check at the top of
        # generation 2 exits -> reports 1.
        g = np.zeros((6, 6), dtype=np.uint8)
        g[3, 3] = 1
        r = oracle.run(g)
        assert r.generations == 1
        assert r.grid.sum() == 0

    def test_check_similarity_off(self):
        block = grid_from_strings(["0000", "0110", "0110", "0000"])
        cfg = GameConfig(gen_limit=5, check_similarity=False)
        r = oracle.run(block, cfg)
        assert r.generations == 5  # still-life no longer exits early

    def test_similarity_frequency_respected(self):
        block = grid_from_strings(["0000", "0110", "0110", "0000"])
        cfg = GameConfig(similarity_frequency=5)
        r = oracle.run(block, cfg)
        assert r.generations == 4  # fires during generation 5, reports 5-1


class TestCudaConvention:
    def test_full_run_counts_match_c(self):
        # Neither convention exits early on a blinker; CUDA reports the same
        # 0-based count after GEN_LIMIT iterations (src/game_cuda.cu:222,294).
        blinker = grid_from_strings(["00000", "00000", "01110", "00000", "00000"])
        c = oracle.run(blinker, GameConfig(gen_limit=10))
        cu = oracle.run(blinker, GameConfig(gen_limit=10, convention=Convention.CUDA))
        assert c.generations == cu.generations == 10
        assert np.array_equal(c.grid, cu.grid)

    def test_empty_exit_keeps_previous_generation(self):
        # CUDA breaks before the swap on emptiness (src/game_cuda.cu:259-268):
        # the written grid is the last non-empty generation and the count is
        # one less than C's.
        g = np.zeros((6, 6), dtype=np.uint8)
        g[3, 3] = 1
        cu = oracle.run(g, GameConfig(convention=Convention.CUDA))
        assert cu.generations == 0
        assert cu.grid.sum() == 1  # pre-evolve grid retained
        c = oracle.run(g)
        assert c.generations == 1
        assert c.grid.sum() == 0

    def test_initially_empty_runs_one_evolve(self):
        # No emptiness test before the first evolve in CUDA.
        cu = oracle.run(np.zeros((4, 4), dtype=np.uint8), GameConfig(convention=Convention.CUDA))
        assert cu.generations == 0
        assert cu.grid.sum() == 0

    def test_similarity_exit_count(self):
        block = grid_from_strings(["0000", "0110", "0110", "0000"])
        cu = oracle.run(block, GameConfig(convention=Convention.CUDA))
        # Breaks during iteration with generation==2 (0-based), prints 2.
        assert cu.generations == 2
        assert np.array_equal(cu.grid, block)


def test_rejects_non_2d():
    with pytest.raises(ValueError):
        oracle.run(np.zeros((2, 2, 2), dtype=np.uint8))


class TestCudaConventionExternalGroundTruth:
    """The cuda accounting pinned by an independent C reimplementation of the
    binary's host loop (src/game_cuda.cu:213-276), compiled at test time —
    the external ground truth the image's missing nvcc would have provided."""

    @pytest.fixture(scope="class")
    def c_binary(self, tmp_path_factory):
        import os
        import shutil
        import subprocess

        cc = next((c for c in ("cc", "gcc", "clang") if shutil.which(c)), None)
        if cc is None:
            pytest.skip("no C toolchain on PATH")
        src = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".claude", "skills", "verify", "cuda_host_loop.c",
        )
        exe = str(tmp_path_factory.mktemp("cbin") / "cuda_host_loop")
        subprocess.run([cc, "-std=c99", "-O2", "-o", exe, src], check=True)
        return exe

    @pytest.mark.parametrize(
        "case", ["random", "still_life", "lone_cell", "all_dead"]
    )
    def test_matches_oracle_and_engine(self, c_binary, case, tmp_path, monkeypatch):
        import subprocess

        from gol_tpu import engine
        from gol_tpu.io import text_grid

        monkeypatch.chdir(tmp_path)
        if case == "random":
            g = np.asarray(text_grid.generate(48, 48, seed=9))
        else:
            g = np.zeros((16, 16), np.uint8)
            if case == "still_life":
                g[4:6, 4:6] = 1
            elif case == "lone_cell":
                g[8, 8] = 1
        text_grid.write_grid("in.txt", g)
        h, w = g.shape
        p = subprocess.run(
            [c_binary, str(w), str(h), "in.txt", "60"],
            capture_output=True, text=True, check=True,
        )
        c_gens = int(
            [l for l in p.stdout.splitlines() if l.startswith("Generations")][0]
            .split("\t")[1]
        )
        c_bytes = open("cuda_output.out", "rb").read()

        config = GameConfig(gen_limit=60, convention=Convention.CUDA)
        expect = oracle.run(g, config)
        text_grid.write_grid("oracle.out", expect.grid)
        assert c_gens == expect.generations
        assert c_bytes == open("oracle.out", "rb").read()

        got = engine.simulate(g, config)
        assert got.generations == c_gens
        text_grid.write_grid("engine.out", got.grid)
        assert c_bytes == open("engine.out", "rb").read()


class TestMpiLoopExternalGroundTruth:
    """The MPI variants' loop accounting pinned by execution, not reading.

    mpicc is absent, so C2-C5 parity rested on reading the C; mpi_loop.c is
    a serial reimplementation of the game_mpi_collective.c driver loop
    (generation=1 init, empty_all at the top of every iteration, halo ->
    evolve -> swap -> post-swap similarity breaking before generation++,
    `generation - 1` reported — src/game_mpi_collective.c:220,331-370),
    compiled and byte-compared here against `--variant collective`."""

    @pytest.fixture(scope="class")
    def c_binary(self, tmp_path_factory):
        import os
        import shutil
        import subprocess

        cc = next((c for c in ("cc", "gcc", "clang") if shutil.which(c)), None)
        if cc is None:
            pytest.skip("no C toolchain on PATH")
        src = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".claude", "skills", "verify", "mpi_loop.c",
        )
        exe = str(tmp_path_factory.mktemp("cbin") / "mpi_loop")
        subprocess.run([cc, "-std=c99", "-O2", "-o", exe, src], check=True)
        return exe

    @pytest.mark.parametrize(
        "case", ["random", "still_life", "lone_cell", "all_dead"]
    )
    def test_matches_collective_variant(
        self, c_binary, case, tmp_path, monkeypatch, capsys
    ):
        import os
        import subprocess

        from gol_tpu import cli
        from gol_tpu.io import text_grid

        monkeypatch.chdir(tmp_path)
        if case == "random":
            g = np.asarray(text_grid.generate(48, 48, seed=21))
        else:
            g = np.zeros((16, 16), np.uint8)
            if case == "still_life":
                g[4:6, 4:6] = 1
            elif case == "lone_cell":
                g[8, 8] = 1
        text_grid.write_grid("in.txt", g)
        h, w = g.shape
        p = subprocess.run(
            [c_binary, str(w), str(h), "in.txt", "60"],
            capture_output=True, text=True, check=True,
        )
        c_gens = int(
            [l for l in p.stdout.splitlines() if l.startswith("Generations")][0]
            .split("\t")[1]
        )
        c_bytes = open("collective_output.out", "rb").read()
        os.rename("collective_output.out", "c_ground_truth.out")

        rc = cli.main(
            [str(w), str(h), "in.txt", "--variant", "collective",
             "--gen-limit", "60"]
        )
        assert rc in (0, None)
        out = capsys.readouterr().out
        our_gens = int(
            [l for l in out.splitlines() if l.startswith("Generations")][0]
            .split("\t")[1]
        )
        assert our_gens == c_gens
        assert open("collective_output.out", "rb").read() == c_bytes

        # The single C convention is exact: the MPI loop's accounting equals
        # the serial oracle's (VERDICT r2 verified the C sources agree; this
        # executes that claim).
        expect = oracle.run(g, GameConfig(gen_limit=60))
        assert expect.generations == c_gens
        text_grid.write_grid("oracle.out", expect.grid)
        assert open("oracle.out", "rb").read() == c_bytes
