"""Sharded I/O tests: per-shard file windows, byte-exact vs the serial codec."""

import numpy as np
import pytest

from gol_tpu import engine, oracle
from gol_tpu.config import GameConfig
from gol_tpu.io import sharded, text_grid
from gol_tpu.parallel import make_mesh


@pytest.fixture
def grid_file(tmp_path):
    g = text_grid.generate(32, 32, seed=11)
    p = tmp_path / "grid.txt"
    text_grid.write_grid(str(p), g)
    return str(p), g


@pytest.mark.parametrize("parallel", [False, True])
def test_read_sharded_matches_serial(grid_file, parallel):
    path, g = grid_file
    mesh = make_mesh(2, 4)
    arr = sharded.read_sharded(path, 32, 32, mesh, parallel=parallel)
    assert np.array_equal(np.asarray(arr), g)
    # Sharding actually spans the mesh.
    assert len(arr.sharding.device_set) == 8


@pytest.mark.parametrize("parallel", [False, True])
def test_write_sharded_byte_exact(grid_file, tmp_path, parallel):
    path, g = grid_file
    mesh = make_mesh(4, 2)
    arr = sharded.read_sharded(path, 32, 32, mesh)
    out = tmp_path / "out.txt"
    sharded.write_sharded(str(out), arr, parallel=parallel)
    assert out.read_bytes() == text_grid.encode(g)


def test_read_sharded_rejects_wrong_size(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_bytes(b"10\n01")  # missing trailing newline: not the exact layout
    with pytest.raises(ValueError, match="exact"):
        sharded.read_sharded(str(p), 2, 2, make_mesh(1, 1))


def test_gathered_roundtrip(grid_file, tmp_path):
    path, g = grid_file
    mesh = make_mesh(2, 2)
    arr = sharded.read_gathered(path, 32, 32, mesh)
    out = tmp_path / "out.txt"
    sharded.write_gathered(str(out), arr)
    assert out.read_bytes() == text_grid.encode(g)


def test_end_to_end_sharded_pipeline(grid_file, tmp_path):
    # read_sharded -> mesh engine -> write_sharded == oracle bytes: the full
    # collective pipeline (src/game_mpi_collective.c) with zero gathers.
    path, g = grid_file
    mesh = make_mesh(2, 4)
    cfg = GameConfig(gen_limit=20)
    arr = sharded.read_sharded(path, 32, 32, mesh)
    result_grid, gen = engine.make_runner((32, 32), cfg, mesh)(arr)
    out = tmp_path / "out.txt"
    sharded.write_sharded(str(out), result_grid)
    want = oracle.run(g, cfg)
    assert int(gen) == want.generations
    assert out.read_bytes() == text_grid.encode(want.grid)
