"""Hardware-pinned kernel evidence: runs ONLY on a real TPU.

The CPU suite covers every kernel in interpret mode; this module re-runs the
compiled Mosaic code paths on the attached chip, turning the "verified on
v5e" claims in the kernel comments (shape caps, narrow-word support, band
picking at the width caps) into executable checks:

    GOL_TPU_HW=1 python -m pytest tests/test_tpu_hw.py -q

Skipped entirely under the default CPU conftest (and anywhere no TPU is
attached), so CI behavior is unchanged.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="hardware lane: needs an attached TPU (GOL_TPU_HW=1, see conftest)",
)

from gol_tpu import engine, oracle  # noqa: E402
from gol_tpu.config import Convention, GameConfig  # noqa: E402
from gol_tpu.io import text_grid  # noqa: E402
from gol_tpu.ops import packed_math, stencil_lax  # noqa: E402
from gol_tpu.ops import stencil_packed as sp  # noqa: E402
from gol_tpu.ops import stencil_pallas as spl  # noqa: E402
from gol_tpu.parallel.mesh import PROXY_2D, SINGLE_DEVICE  # noqa: E402


def _random_words(height, nwords, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.integers(0, np.iinfo(np.uint32).max, size=(height, nwords),
                     dtype=np.uint32, endpoint=True)
    )


@pytest.mark.parametrize(
    "height,nwords",
    [
        (64, 1),     # single-word rows: Mosaic dynamic rotate on logical shape
        (512, 36),   # narrow non-tile-multiple word count (width 1152)
        (256, 128),  # one exact lane tile
        (264, 64),   # height divisible by 8 but not a power of two (band 264)
    ],
)
def test_packed_band_kernel_matches_network(height, nwords):
    words = _random_words(height, nwords)
    new, alive, similar = sp._step(words)
    ref = packed_math.evolve_torus_words(words)
    assert np.array_equal(np.asarray(new), np.asarray(ref))
    assert bool(alive) and not bool(similar)


def test_temporal_kernel_matches_8_network_generations():
    words = _random_words(512, 64, seed=3)
    cur = words
    for _ in range(sp.TEMPORAL_GENS):
        cur = packed_math.evolve_torus_words(cur)
    new, a_vec, s_vec = sp._step_t(words)
    assert np.array_equal(np.asarray(new), np.asarray(cur))
    assert np.asarray(a_vec).tolist() == [1] * sp.TEMPORAL_GENS
    assert np.asarray(s_vec).tolist() == [0] * sp.TEMPORAL_GENS


def test_mesh_form_kernels_match_network():
    # The compiled code a pod shard runs, minus the ppermutes (local wrap).
    # SINGLE_DEVICE (cols == 1) routes the temporal form through the
    # rows-only kernel (_step_trow, the R x 1 pod layout); a cols > 1
    # proxy topology routes the ghost-plane form (_step_tgb, R x C pods).
    words = _random_words(256, 48, seed=4)
    ref1 = packed_math.evolve_torus_words(words)
    new1 = sp._distributed_step(words, SINGLE_DEVICE)[0]
    assert np.array_equal(np.asarray(new1), np.asarray(ref1))

    cur = words
    for _ in range(sp.TEMPORAL_GENS):
        cur = packed_math.evolve_torus_words(cur)
    newt, a_vec, s_vec = sp._distributed_step_multi(words, SINGLE_DEVICE)
    assert np.array_equal(np.asarray(newt), np.asarray(cur))
    assert np.asarray(a_vec).tolist() == [1] * sp.TEMPORAL_GENS

    new2d, a2_vec, _ = sp._distributed_step_multi(words, PROXY_2D)
    assert np.array_equal(np.asarray(new2d), np.asarray(cur))
    assert np.asarray(a2_vec).tolist() == [1] * sp.TEMPORAL_GENS


def test_mesh_temporal_single_word_branch():
    # nwords == 1 compiled on hardware, both mesh forms: rows-only (the
    # lane roll degenerates to the identity, in-word bit wrap only) and the
    # ghost-plane form (gw and ge patches both target lane 0).
    words = _random_words(64, 1, seed=8)
    cur = words
    for _ in range(sp.TEMPORAL_GENS):
        cur = packed_math.evolve_torus_words(cur)
    newt, a_vec, _ = sp._distributed_step_multi(words, SINGLE_DEVICE)
    assert np.array_equal(np.asarray(newt), np.asarray(cur))
    assert np.asarray(a_vec).tolist() == [1] * sp.TEMPORAL_GENS
    new2d, _, _ = sp._distributed_step_multi(words, PROXY_2D)
    assert np.array_equal(np.asarray(new2d), np.asarray(cur))


def test_packed_width_cap_compiles_and_matches():
    # The _MAX_WORDS=32768 empirical gate (width 2^20): compiles on v5e and
    # matches the jnp network; re-probe when raising the cap or growing the
    # kernel's live set.
    nwords = sp._MAX_WORDS
    assert sp.supports(64, nwords * 32, SINGLE_DEVICE)
    words = _random_words(64, nwords, seed=5)
    new = sp._step(words)[0]
    ref = packed_math.evolve_torus_words(words)
    assert np.array_equal(np.asarray(new), np.asarray(ref))


def test_temporal_width_cap_compiles_and_matches():
    # The _MAX_WORDS_T=8192 empirical gate (width 2^18) at the
    # _bandt_target 1MB band target (32-row bands; the 2MB target's 64-row
    # bands blow scoped VMEM by 1.73M here). EVERY temporal form must
    # compile at the cap — supports_multi admits them all, and the rows-
    # only (n, 1) default mesh makes full-width shards at the cap the
    # routine case, not a corner.
    nwords = sp._MAX_WORDS_T
    assert sp.supports_multi(1024, nwords * 32, SINGLE_DEVICE)
    words = _random_words(1024, nwords, seed=6)
    cur = words
    for _ in range(sp.TEMPORAL_GENS):
        cur = packed_math.evolve_torus_words(cur)
    new = sp._step_t(words)[0]
    assert np.array_equal(np.asarray(new), np.asarray(cur))
    # Mesh forms at the cap: rows-only (what an (n, 1) shard runs) and the
    # ghost-plane form (R x C shards) — larger live sets than _step_t.
    new_rows = sp._distributed_step_multi(words, SINGLE_DEVICE)[0]
    assert np.array_equal(np.asarray(new_rows), np.asarray(cur))
    new_2d = sp._distributed_step_multi(words, PROXY_2D)[0]
    assert np.array_equal(np.asarray(new_2d), np.asarray(cur))


def test_byte_band_kernel_matches_lax():
    rng = np.random.default_rng(7)
    grid = jnp.asarray(rng.integers(0, 2, size=(256, 512), dtype=np.uint8))
    new = spl._step(grid)[0]
    ref = stencil_lax.evolve_torus(grid)
    assert np.array_equal(np.asarray(new), np.asarray(ref))


@pytest.mark.parametrize("convention", [Convention.C, Convention.CUDA])
def test_engine_end_to_end_vs_oracle(convention):
    g = text_grid.generate(256, 256, seed=11)
    cfg = GameConfig(gen_limit=100, convention=convention)
    got = engine.simulate(g, cfg, kernel="auto")
    want = oracle.run(g, cfg)
    assert got.generations == want.generations
    assert np.array_equal(got.grid, want.grid)


def test_temporal_near_cap_widths_compile_and_match():
    # The advisor's just-under-cap probes: 7680 words (where the r3 rule's
    # 2MB target Mosaic-OOMed, benchmarks/vmem_probe_r4.json) and 8184 (a
    # non-tile-multiple row). The width-continuous _bandt_target must pick
    # compiling bands for every temporal form, and results must match the
    # jnp network.
    for nwords in (7680, 8184):
        words = _random_words(64, nwords, seed=8)
        cur = words
        for _ in range(sp.TEMPORAL_GENS):
            cur = packed_math.evolve_torus_words(cur)
        assert np.array_equal(np.asarray(sp._step_t(words)[0]), np.asarray(cur)), nwords
        rows = sp._distributed_step_multi(words, SINGLE_DEVICE)[0]
        assert np.array_equal(np.asarray(rows), np.asarray(cur)), nwords
        two_d = sp._distributed_step_multi(words, PROXY_2D)[0]
        assert np.array_equal(np.asarray(two_d), np.asarray(cur)), nwords


def test_split_edge_form_compiled_matches():
    # The r4 split-edge 2D form compiled on the chip (not interpret mode):
    # random soup exercises main-pass torus rolls, the lane-folded strip,
    # the stitch, and the combined flags.
    rng = np.random.default_rng(13)
    g = rng.integers(0, 2, size=(512, 4096), dtype=np.uint8)
    words = sp.encode(jnp.asarray(g))
    gtop, gbot, cols4, G_ext = sp._tsplit_operands(words, SINGLE_DEVICE)
    new, alive, similar = sp._step_tsplit(words, gtop, gbot, cols4, G_ext)
    cur = words
    for _ in range(sp.TEMPORAL_GENS):
        cur = packed_math.evolve_torus_words(cur)
    assert np.array_equal(np.asarray(new), np.asarray(cur))
    assert np.asarray(alive).tolist() == [1] * sp.TEMPORAL_GENS


def test_split_fast_form_compiled_matches():
    # The r5 fast-flag split composition compiled on the chip: joint
    # strip+main summaries on soup (no replay), and a mid-pass death with
    # the transient INSIDE an edge word column — the strip summary alone
    # sees the in_alive -> out_alive transition, so the joint derivation
    # must fire the exact-replay lax.cond and reproduce the oracle's flag
    # vectors.
    rng = np.random.default_rng(19)
    g = rng.integers(0, 2, size=(512, 4096), dtype=np.uint8)
    words = sp.encode(jnp.asarray(g))
    ops = sp._tsplit_operands(words, SINGLE_DEVICE)
    new, alive, similar = sp._step_tsplit_fast(words, *ops)
    cur = words
    for _ in range(sp.TEMPORAL_GENS):
        cur = packed_math.evolve_torus_words(cur)
    assert np.array_equal(np.asarray(new), np.asarray(cur))
    assert np.asarray(alive).tolist() == [1] * sp.TEMPORAL_GENS
    assert np.asarray(similar).tolist() == [0] * sp.TEMPORAL_GENS

    g2 = np.zeros((512, 4096), np.uint8)
    g2[100, 4064:4066] = 1  # domino in the east edge word: dies at gen 1
    words2 = sp.encode(jnp.asarray(g2))
    ops2 = sp._tsplit_operands(words2, SINGLE_DEVICE)
    _, a_vec, s_vec = sp._step_tsplit_fast(words2, *ops2)
    assert np.asarray(a_vec).tolist() == [0] * sp.TEMPORAL_GENS
    assert np.asarray(s_vec).tolist() == [0] + [1] * (sp.TEMPORAL_GENS - 1)


def test_fast_flag_pass_shapes_compile_and_match():
    # The fast-flag kernels' scoped-VMEM footprint is schedule-sensitive
    # (1024/2048-row bands OOMed where the exact kernel fit — hence the
    # 512-row _fast_target cap); pin the capped configs on hardware,
    # including the tall-narrow shape that exposed the hazard.
    for shape in ((2048, 256), (512, 2048), (64, 8192)):
        words = _random_words(*shape, seed=17)
        cur = words
        for _ in range(sp.TEMPORAL_GENS):
            cur = packed_math.evolve_torus_words(cur)
        new, a_vec, s_vec = sp._step_t_fast(words)
        assert np.array_equal(np.asarray(new), np.asarray(cur)), shape
        assert np.asarray(a_vec).tolist() == [1] * sp.TEMPORAL_GENS, shape
        assert np.asarray(s_vec).tolist() == [0] * sp.TEMPORAL_GENS, shape
    # An in-pass exit on hardware: a domino dies at generation 1 — the
    # lax.cond exact replay must produce the oracle's flag vectors: dead
    # from slot 0, and similar (empty == empty) from slot 1 on.
    g = np.zeros((256, 2048), np.uint8)
    g[100, 100:102] = 1
    words = sp.encode(jnp.asarray(g))
    _, a_vec, s_vec = sp._step_t_fast(words)
    assert np.asarray(a_vec).tolist() == [0] * sp.TEMPORAL_GENS
    assert np.asarray(s_vec).tolist() == [0] + [1] * (sp.TEMPORAL_GENS - 1)
