"""Worker process for the 2-process multi-host test.

The ``mpiexec -n 2`` analog (README.md:54-57): each OS process joins the
cluster through ``bootstrap.initialize`` (MPI_Init,
src/game_mpi_collective.c:116-118), contributes its own CPU device to the
('row', 'col') mesh, reads only its addressable file windows, runs the
engine's shard_map program (halo ppermute + psum votes riding the gloo
cross-process collectives), and writes only its addressable windows of the
shared output file — no process ever holds the full grid.

Invoked by tests/test_multihost.py as:
    python multihost_worker.py <port> <process_id> <num_processes> <workdir>
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    port, pid, nprocs, workdir = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4],
    )
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import jax

    jax.config.update("jax_platforms", "cpu")

    from gol_tpu.parallel import bootstrap

    bootstrap.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=pid,
    )
    assert jax.process_count() == nprocs, jax.process_count()
    assert bootstrap.is_multihost()

    from gol_tpu import engine
    from gol_tpu.config import GameConfig
    from gol_tpu.io import sharded
    from gol_tpu.parallel.mesh import make_mesh

    height = width = 64
    config = GameConfig(gen_limit=40)
    # One device per process: mesh axes ARE process boundaries, so the halo
    # ppermute crosses processes every generation — E/W only for a 1xN world,
    # both axes for a 2x2 world (the full Cartesian topology of
    # src/game_mpi_collective.c:125-133 with one rank per host).
    rows = 2 if nprocs == 4 else 1
    mesh = make_mesh(rows, nprocs // rows)

    for kernel in ("lax", "packed"):
        device_grid = sharded.read_sharded(
            os.path.join(workdir, "input.txt"), width, height, mesh
        )
        runner = engine.make_runner((height, width), config, mesh, kernel)
        final, gen = runner(device_grid)
        generations = int(gen)
        sharded.write_sharded(os.path.join(workdir, f"out_{kernel}.txt"), final)
        if pid == 0:
            with open(os.path.join(workdir, f"gens_{kernel}.txt"), "w") as f:
                f.write(str(generations))

    # The master-scatter lane (C2, --variant mpi): every process parses the
    # whole input (the scatter), and the gather-to-lead write reassembles
    # the grid across processes via process_allgather — the one lane whose
    # I/O is NOT window-disjoint, matching src/game_mpi.c:201-239,429-467.
    device_grid = sharded.read_gathered(
        os.path.join(workdir, "input.txt"), width, height, mesh
    )
    runner = engine.make_runner((height, width), config, mesh, "packed")
    final, gen = runner(device_grid)
    generations = int(gen)
    sharded.write_gathered(os.path.join(workdir, "out_mpi.txt"), final)
    if pid == 0:
        with open(os.path.join(workdir, "gens_mpi.txt"), "w") as f:
            f.write(str(generations))

    # The packed-I/O lane (C3's MPI-IO at word granularity): each process
    # packs/unpacks only its addressable file windows, word state end to end.
    from gol_tpu.io import packed_io

    words = packed_io.read_packed(
        os.path.join(workdir, "input.txt"), width, height, mesh
    )
    runner = engine.make_packed_runner((height, width), config, mesh)
    final_words, gen = runner(words)
    generations = int(gen)
    packed_io.write_packed(
        os.path.join(workdir, "out_packedio.txt"), final_words, width
    )
    if pid == 0:
        with open(os.path.join(workdir, "gens_packedio.txt"), "w") as f:
            f.write(str(generations))

    # The TensorStore lane's multi-writer discipline under real processes:
    # lead-process create + device barrier, every process writing only its
    # addressable shards into shard-aligned chunks, then a sharded read-back
    # unpacked through the codec so the parent can byte-compare.
    from gol_tpu.io import ts_store

    if ts_store.HAVE_TENSORSTORE:
        store_path = os.path.join(workdir, "out_words.zarr")
        ts_store.write_words(store_path, final_words, width)
        back = ts_store.read_words(store_path, width, height, mesh)
        packed_io.write_packed(
            os.path.join(workdir, "out_tsstore.txt"), back, width
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
