"""Resilience subsystem: unified retry policy, fault plans, atomic
checkpoints, and resume-equivalence (interrupt anywhere, replay exactly).

The checkpoint tests drive CheckpointManager with a plain numpy codec so the
crash-ordering argument (payload first, manifest committed atomically last,
GC after) is pinned independently of any tensorstore/jax IO stack; the CLI
end-to-end harness lives in tests/test_crash_recovery.py.
"""

import json
import os
import zlib

import numpy as np
import pytest

from gol_tpu import engine, oracle
from gol_tpu.config import Convention, GameConfig
from gol_tpu.parallel.collectives import host_all_agree
from gol_tpu.resilience import faults
from gol_tpu.resilience.checkpoint import CheckpointManager, PayloadCodec
from gol_tpu.resilience.faults import (
    FaultPlan,
    InjectedCrash,
    InjectedWriteError,
    TransientInjectedError,
)
from gol_tpu.resilience.retry import RetryPolicy, is_transient_io


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends with no fault plan armed."""
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# RetryPolicy


class TestRetryPolicy:
    def test_first_try_success_no_sleep(self):
        sleeps = []
        out = RetryPolicy(attempts=3).call(lambda: 42, sleep=sleeps.append)
        assert out == 42
        assert sleeps == []

    def test_transient_failures_heal(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("connection reset by peer")
            return "ok"

        sleeps = []
        out = RetryPolicy(attempts=3, base_delay=0.05, multiplier=2.0).call(
            flaky, sleep=sleeps.append
        )
        assert out == "ok"
        assert calls["n"] == 3
        assert sleeps == [0.05, 0.1]

    def test_non_retryable_raises_immediately(self):
        calls = {"n": 0}

        def bad():
            calls["n"] += 1
            raise ValueError("shape mismatch UNAVAILABLE")  # text is a decoy

        with pytest.raises(ValueError):
            RetryPolicy(attempts=5, base_delay=0).call(bad)
        assert calls["n"] == 1

    def test_attempts_exhausted_raises_last_error(self):
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise OSError(f"timed out #{calls['n']}")

        with pytest.raises(OSError, match="#3"):
            RetryPolicy(attempts=3, base_delay=0).call(always)
        assert calls["n"] == 3

    def test_backoff_caps_at_max_delay(self):
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise OSError("try again")

        sleeps = []
        with pytest.raises(OSError):
            RetryPolicy(
                attempts=5, base_delay=0.1, multiplier=4.0, max_delay=0.5
            ).call(always, sleep=sleeps.append)
        assert sleeps == [0.1, 0.4, 0.5, 0.5]

    def test_deadline_stops_retrying(self):
        now = {"t": 0.0}

        def clock():
            return now["t"]

        def sleep(d):
            now["t"] += d

        calls = {"n": 0}

        def always():
            calls["n"] += 1
            now["t"] += 1.0  # each attempt costs a second
            raise OSError("timed out")

        with pytest.raises(OSError):
            RetryPolicy(attempts=10, base_delay=0.1, deadline=2.5).call(
                always, sleep=sleep, clock=clock
            )
        assert calls["n"] < 10  # the deadline cut the attempts short

    def test_on_retry_observes_each_backoff(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("temporarily unavailable")
            return 1

        seen = []
        RetryPolicy(attempts=3, base_delay=0).call(
            flaky, on_retry=lambda a, e, d: seen.append(a)
        )
        assert seen == [1, 2]

    def test_invalid_policy_rejected(self):
        with pytest.raises(ValueError):
            RetryPolicy(attempts=0)
        with pytest.raises(ValueError):
            RetryPolicy(base_delay=-1)

    def test_is_transient_io_classification(self):
        assert is_transient_io(OSError("Connection reset by peer"))
        assert is_transient_io(OSError("DEADLINE_EXCEEDED while writing"))
        assert is_transient_io(TransientInjectedError("somewhere"))
        assert not is_transient_io(InjectedWriteError("somewhere"))
        assert not is_transient_io(OSError("no space left on device"))
        # ValueError never heals on retry, whatever its text claims.
        assert not is_transient_io(ValueError("UNAVAILABLE"))


# ---------------------------------------------------------------------------
# FaultPlan


class TestFaultPlan:
    def test_parse_spec(self):
        plan = FaultPlan.parse(
            "ts_write_fail=2,ts_write_error=transient,kill_at_gen=5"
        )
        assert plan.ts_write_fail == 2
        assert plan.ts_write_error == "transient"
        assert plan.kill_at_gen == 5
        assert plan.kill_mode == "exception"

    def test_parse_rejects_unknown_key(self):
        with pytest.raises(ValueError, match="unknown fault plan key"):
            FaultPlan.parse("ts_write_fial=2")

    def test_parse_rejects_bad_enum_and_shape(self):
        with pytest.raises(ValueError, match="kill_mode"):
            FaultPlan.parse("kill_mode=nuke")
        with pytest.raises(ValueError, match="not k=v"):
            FaultPlan.parse("kill_at_gen")

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("GOL_FAULTS", "payload_write_fail=1")
        plan = FaultPlan.from_env()
        assert plan is not None and plan.payload_write_fail == 1
        monkeypatch.delenv("GOL_FAULTS")
        assert FaultPlan.from_env() is None

    def test_disarmed_probes_are_noops(self):
        faults.on_ts_open()
        faults.on_ts_shard_write(0)
        faults.on_payload_write("/x")
        faults.on_checkpoint_boundary(10**9)

    def test_nth_shard_write_fails(self):
        faults.install(FaultPlan(ts_write_fail=2))
        faults.on_ts_shard_write(0)
        with pytest.raises(InjectedWriteError, match="shard 7"):
            faults.on_ts_shard_write(7)
        faults.on_ts_shard_write(8)  # only the Nth fails

    def test_transient_shard_write_mode(self):
        faults.install(FaultPlan(ts_write_fail=1, ts_write_error="transient"))
        with pytest.raises(TransientInjectedError):
            faults.on_ts_shard_write(0)

    def test_open_transient_burst(self):
        faults.install(FaultPlan(ts_open_transient=2))
        for _ in range(2):
            with pytest.raises(TransientInjectedError):
                faults.on_ts_open()
        faults.on_ts_open()  # the burst is over

    def test_kill_at_boundary_fires_once(self):
        faults.install(FaultPlan(kill_at_gen=6))
        faults.on_checkpoint_boundary(3)
        with pytest.raises(InjectedCrash):
            faults.on_checkpoint_boundary(6)
        # A resumed run re-reaching boundaries must not be re-killed.
        faults.on_checkpoint_boundary(9)

    def test_injected_crash_evades_except_exception(self):
        # The whole point: library-level `except Exception` must not absorb
        # a simulated SIGKILL.
        assert not issubclass(InjectedCrash, Exception)


# ---------------------------------------------------------------------------
# CheckpointManager (numpy codec: jax/tensorstore-independent ordering tests)


def _np_codec() -> PayloadCodec:
    return PayloadCodec(
        format="npy",
        suffix=".npy",
        write=lambda path, state: np.save(path, np.asarray(state)),
        read=lambda path: np.load(path),
    )


def _mgr(directory, keep=2, h=8, w=8, fingerprint=None) -> CheckpointManager:
    return CheckpointManager(
        str(directory), height=h, width=w, codec=_np_codec(), keep=keep,
        run_fingerprint=fingerprint,
    )


def _grid(seed, h=8, w=8):
    return np.random.default_rng(seed).integers(0, 2, size=(h, w)).astype(np.uint8)


class TestCheckpointManager:
    def test_save_restore_roundtrip(self, tmp_path):
        mgr = _mgr(tmp_path)
        g = _grid(1)
        mgr.save(g, 5, 2)
        state, info = mgr.restore()
        np.testing.assert_array_equal(np.asarray(state), g)
        assert (info.generation, info.counter) == (5, 2)

    def test_empty_dir_restores_none(self, tmp_path):
        assert _mgr(tmp_path).restore() is None

    def test_payload_without_manifest_is_invisible(self, tmp_path):
        mgr = _mgr(tmp_path)
        np.save(os.path.join(str(tmp_path), "ckpt-00000007.npy"), _grid(2))
        assert mgr.restore() is None

    def test_gc_keeps_newest_k(self, tmp_path):
        mgr = _mgr(tmp_path, keep=2)
        for gen in (3, 6, 9):
            mgr.save(_grid(gen), gen, 0)
        names = sorted(os.listdir(tmp_path))
        assert names == [
            "ckpt-00000006.manifest.json",
            "ckpt-00000006.npy",
            "ckpt-00000009.manifest.json",
            "ckpt-00000009.npy",
        ]

    def test_gc_sweeps_stale_staging_leftovers(self, tmp_path):
        mgr = _mgr(tmp_path)
        mgr.save(_grid(3), 3, 0)
        # A codec writer crashed mid-payload on a previous run: ckpt-prefixed
        # staging leftovers must be swept by the next save's GC, not leak one
        # grid-sized file per crash.
        stale = ("ckpt-00000006.npy.inprogress", "ckpt-00000003.npy.replaced",
                 "ckpt-00000006.manifest.json.tmp")
        for name in stale:
            with open(os.path.join(str(tmp_path), name), "wb") as f:
                f.write(b"torn")
        mgr.save(_grid(6), 6, 0)
        names = sorted(os.listdir(tmp_path))
        assert names == [
            "ckpt-00000003.manifest.json",
            "ckpt-00000003.npy",
            "ckpt-00000006.manifest.json",
            "ckpt-00000006.npy",
        ]

    def test_corrupt_newest_falls_back_to_older(self, tmp_path):
        mgr = _mgr(tmp_path)
        g3, g6 = _grid(3), _grid(6)
        mgr.save(g3, 3, 0)
        mgr.save(g6, 6, 0)
        # Silent payload corruption: a valid .npy holding the WRONG bytes —
        # only the manifest checksums can catch it.
        np.save(os.path.join(str(tmp_path), "ckpt-00000006.npy"), _grid(999))
        state, info = mgr.restore()
        assert info.generation == 3
        np.testing.assert_array_equal(np.asarray(state), g3)

    def test_torn_manifest_falls_back(self, tmp_path):
        mgr = _mgr(tmp_path)
        mgr.save(_grid(3), 3, 0)
        mgr.save(_grid(6), 6, 0)
        manifest = os.path.join(str(tmp_path), "ckpt-00000006.manifest.json")
        with open(manifest, "w") as f:
            f.write('{"format_version": 1, "generation"')  # torn mid-write
        state, info = mgr.restore()
        assert info.generation == 3

    def test_geometry_mismatch_rejected(self, tmp_path):
        _mgr(tmp_path, h=8, w=8).save(_grid(4), 4, 0)
        assert _mgr(tmp_path, h=16, w=16).restore() is None

    def test_resave_of_committed_generation_is_noop(self, tmp_path):
        mgr = _mgr(tmp_path)
        mgr.save(_grid(5), 5, 1)
        manifest = os.path.join(str(tmp_path), "ckpt-00000005.manifest.json")
        before = open(manifest, "rb").read()
        mgr.save(_grid(5), 5, 1)  # a resumed run re-reaching the boundary
        assert open(manifest, "rb").read() == before

    def test_manifest_records_checksums_and_geometry(self, tmp_path):
        mgr = _mgr(tmp_path)
        mgr.save(_grid(5), 5, 1)
        with open(os.path.join(str(tmp_path), "ckpt-00000005.manifest.json")) as f:
            m = json.load(f)
        assert m["height"] == 8 and m["width"] == 8
        assert m["payload"] == "ckpt-00000005.npy"
        assert m["checksums"]  # at least one block CRC

    def test_midwrite_failure_keeps_prior_restorable(self, tmp_path):
        mgr = _mgr(tmp_path)
        g3 = _grid(3)
        mgr.save(g3, 3, 0)
        faults.install(FaultPlan(payload_write_fail=1))
        with pytest.raises(InjectedWriteError):
            mgr.save(_grid(6), 6, 0)
        faults.clear()
        # The fault TORE the gen-6 payload mid-file and aborted before the
        # manifest commit: the torn payload is invisible garbage, gen 3
        # intact.
        torn = os.path.join(str(tmp_path), "ckpt-00000006.npy")
        intact = os.path.join(str(tmp_path), "ckpt-00000003.npy")
        assert os.path.exists(torn)
        # Genuinely truncated: half the bytes of the intact sibling payload.
        assert os.path.getsize(torn) < os.path.getsize(intact)
        state, info = mgr.restore()
        assert info.generation == 3
        np.testing.assert_array_equal(np.asarray(state), g3)
        # And a healthy retry of the same boundary goes through.
        g6 = _grid(6)
        mgr.save(g6, 6, 0)
        state, info = mgr.restore()
        assert info.generation == 6
        np.testing.assert_array_equal(np.asarray(state), g6)

    def test_kill_at_boundary_preserves_prior(self, tmp_path):
        mgr = _mgr(tmp_path)
        mgr.save(_grid(3), 3, 0)
        faults.install(FaultPlan(kill_at_gen=6))
        with pytest.raises(InjectedCrash):
            mgr.save(_grid(6), 6, 0)
        faults.clear()
        state, info = mgr.restore()
        assert info.generation == 3

    def test_keep_must_be_positive(self, tmp_path):
        with pytest.raises(ValueError, match="keep"):
            _mgr(tmp_path, keep=0)

    def test_foreign_run_checkpoints_invisible_and_collected(self, tmp_path):
        # Run A leaves checkpoints in the dir; run B (different input, same
        # geometry) must never restore A's state, and A's numerically-newer
        # generations must neither shadow nor out-sort B's fresh ones.
        a = _mgr(tmp_path, fingerprint="run-a")
        a.save(_grid(1), 6, 0)
        a.save(_grid(2), 9, 0)
        b = _mgr(tmp_path, fingerprint="run-b")
        assert b.restore() is None
        g3 = _grid(3)
        b.save(g3, 3, 0)  # GC sweeps A's leftovers, keeps B's gen 3
        state, info = b.restore()
        assert info.generation == 3
        np.testing.assert_array_equal(np.asarray(state), g3)
        assert sorted(os.listdir(tmp_path)) == [
            "ckpt-00000003.manifest.json",
            "ckpt-00000003.npy",
        ]

    def test_restore_max_generation_skips_newer(self, tmp_path):
        mgr = _mgr(tmp_path)
        g6 = _grid(6)
        mgr.save(g6, 6, 0)
        mgr.save(_grid(9), 9, 0)
        state, info = mgr.restore(max_generation=8)
        assert info.generation == 6
        np.testing.assert_array_equal(np.asarray(state), g6)
        assert mgr.restore(max_generation=5) is None

    def test_run_fingerprint_is_input_sensitive(self):
        from gol_tpu.resilience.checkpoint import run_fingerprint

        g = _grid(1)
        assert run_fingerprint(g) == run_fingerprint(g.copy())
        assert run_fingerprint(g) != run_fingerprint(_grid(2))
        assert run_fingerprint(g, tag="c") != run_fingerprint(g, tag="cuda")
        # Positional, not just multiset: a transposed grid must not collide.
        gt = np.ascontiguousarray(g.T)
        assert (g != gt).any() and run_fingerprint(g) != run_fingerprint(gt)

    def test_run_fingerprint_decomposition_independent(self):
        # The same state under ANY shard decomposition must fingerprint
        # identically — a rerun on a different mesh still recognizes its own
        # checkpoints instead of GC-ing them as foreign.
        from gol_tpu.resilience.checkpoint import run_fingerprint

        g = _grid(7)

        def sharded(cuts):
            shards = [
                type("S", (), {"data": g[rs, cs], "index": (rs, cs)})()
                for rs, cs in cuts
            ]
            return type("A", (), {"shape": g.shape,
                                  "addressable_shards": shards})()

        rows = sharded([(slice(0, 4), slice(0, 8)), (slice(4, 8), slice(0, 8))])
        cols = sharded([(slice(0, 8), slice(0, 4)), (slice(0, 8), slice(4, 8))])
        quads = sharded([
            (slice(0, 4), slice(0, 4)), (slice(0, 4), slice(4, 8)),
            (slice(4, 8), slice(0, 4)), (slice(4, 8), slice(4, 8)),
        ])
        whole = run_fingerprint(g)
        assert run_fingerprint(rows) == whole
        assert run_fingerprint(cols) == whole
        assert run_fingerprint(quads) == whole

    def test_run_fingerprint_limb_merge_is_exact_mod_2_64(self):
        # Review regression: the old two-31-bit-halves exchange dropped bits
        # 62-63 of every process's partial, so the merged fingerprint
        # depended on the shard decomposition and a rerun on a different
        # mesh GC'd its own checkpoints as foreign. The limb exchange must
        # reconstruct sum(partials) mod 2**64 EXACTLY, high bits included.
        from gol_tpu.resilience.checkpoint import (
            _fingerprint_limbs,
            _merge_fingerprint_limbs,
        )

        rng = np.random.default_rng(7)
        for n_proc in (1, 2, 3, 8):
            partials = [
                int(rng.integers(0, 1 << 64, dtype=np.uint64)) | (0b11 << 62)
                for _ in range(n_proc)
            ]
            everyone = np.stack([_fingerprint_limbs(p) for p in partials])
            want = sum(partials) & ((1 << 64) - 1)
            assert _merge_fingerprint_limbs(everyone) == want

    def test_verify_checksums_multihost_is_local_and_reports_coverage(
        self, monkeypatch
    ):
        # Review regression: on a topology where the writer's recorded
        # blocks straddle every local shard, zero blocks were checked and
        # verification passed vacuously. _verify_checksums now reports
        # which keys it actually checked (collective-free) so the vote can
        # refuse blocks nobody covers.
        import jax

        from gol_tpu.resilience import checkpoint as cp

        g = _grid(3)

        def sharded(cuts):
            shards = [
                type("S", (), {"data": g[rs, cs], "index": (rs, cs)})()
                for rs, cs in cuts
            ]
            return type("A", (), {"shape": g.shape,
                                  "addressable_shards": shards})()

        state = sharded([(slice(0, 4), slice(0, 8)),
                         (slice(4, 8), slice(0, 8))])
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        # Writer's whole-grid block straddles both local shards, but the two
        # shards TILE its region: it must be assembled and verified (elastic
        # restore onto a finer local mesh).
        whole_key = cp._block_key(0, 8, 0, 8)
        whole = {whole_key: zlib.crc32(np.ascontiguousarray(g).tobytes())}
        assert cp._verify_checksums(state, whole) == (True, {whole_key})
        # Writer blocks nested in local shards verify and report coverage.
        k_top, k_bot = cp._block_key(0, 4, 0, 8), cp._block_key(4, 8, 0, 8)
        nested = {
            k_top: zlib.crc32(np.ascontiguousarray(g[0:4]).tobytes()),
            k_bot: zlib.crc32(np.ascontiguousarray(g[4:8]).tobytes()),
        }
        assert cp._verify_checksums(state, nested) == (True, {k_top, k_bot})
        bad = dict(nested)
        bad[k_top] ^= 1
        assert cp._verify_checksums(state, bad) == (False, {k_bot})
        # A block partly owned by a peer process is skipped, not failed —
        # visible as an uncovered key for the vote to pool.
        half = sharded([(slice(0, 4), slice(0, 8))])
        assert cp._verify_checksums(half, whole) == (True, set())
        assert cp._verify_checksums(half, nested) == (True, {k_top})

    def test_collective_is_valid_votes_once_per_process(
        self, tmp_path, monkeypatch, caplog
    ):
        # Review regression: the cluster verdict must be ONE collective
        # every process reaches — including one whose _load returned None —
        # and recorded blocks no process verified must be loudly logged,
        # never silently counted as verified (nor refused outright, which
        # would break cross-mesh restore and restart valid runs from 0).
        import logging

        import jax

        from gol_tpu.resilience import checkpoint as cp

        mgr = _mgr(tmp_path)
        info = cp.CheckpointInfo(generation=1, counter=0, path="m")

        def loaded(local_ok, verified, recorded):
            return cp._LoadedCheckpoint(
                state=None, info=info, local_ok=local_ok,
                verified=frozenset(verified), recorded=frozenset(recorded))

        gathered = []
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(cp, "_allgather_json",
                            lambda obj: gathered.append(obj) or [obj])
        # Full coverage, all OK -> valid, no unverified warning.
        with caplog.at_level(logging.WARNING, logger="gol_tpu"):
            assert mgr._collective_is_valid(
                loaded(True, {"a", "b"}, {"a", "b"}))
        assert "UNVERIFIED" not in caplog.text
        # A recorded block nobody verified -> restored anyway, loudly.
        with caplog.at_level(logging.WARNING, logger="gol_tpu"):
            assert mgr._collective_is_valid(loaded(True, {"a"}, {"a", "b"}))
        assert "1/2 recorded block(s) CRC-UNVERIFIED" in caplog.text
        # A local CRC mismatch -> refused (but the collective still ran).
        assert not mgr._collective_is_valid(loaded(False, {"b"}, {"a", "b"}))
        # A failed _load STILL votes (None must not skip the collective —
        # peers' allgathers would pair with whatever runs next and hang).
        assert not mgr._collective_is_valid(None)
        assert len(gathered) == 4
        assert gathered[-1] == [False, []]

    def test_multihost_write_failure_aborts_before_collectives(
        self, tmp_path, monkeypatch
    ):
        # Review regression: one process's failed shard write must vote the
        # whole cluster out of the checkpoint BEFORE the checksum allgather
        # and commit barriers — not exit save() alone and leave its peers
        # hung there until the distributed-runtime timeout.
        import jax
        from jax.experimental import multihost_utils

        from gol_tpu.parallel import collectives
        from gol_tpu.resilience import checkpoint as cp

        mgr = _mgr(tmp_path)
        g5 = _grid(5)
        mgr.save(g5, 5, 0)  # prior durable checkpoint

        votes = []
        with monkeypatch.context() as m:
            m.setattr(jax, "process_count", lambda: 2)
            m.setattr(jax, "process_index", lambda: 0)
            m.setattr(multihost_utils, "sync_global_devices",
                      lambda name: None)
            m.setattr(collectives, "host_all_agree",
                      lambda flag: votes.append(flag) or flag)
            m.setattr(cp, "_allgather_json", lambda obj: [obj])
            m.setattr(cp, "_allgather_checksums",
                      lambda sums: pytest.fail(
                          "entered the checksum collective after a write "
                          "failure — peers would hang"))
            faults.install(FaultPlan(payload_write_fail=1))
            with pytest.raises(InjectedWriteError):
                mgr.save(_grid(9), 9, 0)
        assert votes[-1] is False  # the failing process voted, then raised
        # The abandoned checkpoint never shadowed the durable one.
        state, info = mgr.restore()
        assert info.generation == 5
        np.testing.assert_array_equal(np.asarray(state), g5)


def test_host_all_agree_single_process():
    assert host_all_agree(True) is True
    assert host_all_agree(False) is False


# ---------------------------------------------------------------------------
# Resume equivalence: interrupting at EVERY generation k and resuming via
# resume_scalars reproduces the uninterrupted run — output grid, generation
# count, and exit reason — on both the similarity-exit and limit-exit paths.


def _run_to_end(state, config, completed):
    last = None
    for out in engine.simulate_segments(
        state, config, None, "lax", segment=5, completed=completed
    ):
        last = out
    return last


def _check_resume_at_every_generation(g, config):
    ref = oracle.run(g, config)
    interior = []  # (completed_generations, state) at every interrupt point
    last = None
    for gens, state, stopped in engine.simulate_segments(g, config, None, "lax", 1):
        if not stopped:
            interior.append((gens, np.asarray(state, np.uint8)))
        last = (gens, np.asarray(state, np.uint8), stopped)
    gens, final, stopped = last
    assert stopped and gens == ref.generations
    np.testing.assert_array_equal(final, ref.grid)

    for completed, state_k in interior:
        rgens, rfinal, rstopped = _run_to_end(state_k, config, completed)
        assert rstopped
        assert rgens == ref.generations, (
            f"resume at k={completed} reported {rgens}, "
            f"uninterrupted reported {ref.generations}"
        )
        np.testing.assert_array_equal(np.asarray(rfinal, np.uint8), ref.grid)
    return ref


@pytest.mark.parametrize("convention", [Convention.C, Convention.CUDA])
def test_resume_equivalence_limit_exit(convention):
    g = _grid(13, 16, 16)
    config = GameConfig(gen_limit=18, convention=convention)
    ref = _check_resume_at_every_generation(g, config)
    # Scenario sanity: this grid actually runs to the limit.
    assert ref.generations == config.gen_limit


@pytest.mark.parametrize("convention", [Convention.C, Convention.CUDA])
def test_resume_equivalence_similarity_exit(convention):
    # This grid settles into still lifes and similarity-exits at generation
    # 23 under both conventions — every interrupt point k < 23 must replay
    # through the exit machinery to the same early-exit generation.
    from gol_tpu.io import text_grid

    g = text_grid.generate(16, 16, seed=26, density=0.25)
    config = GameConfig(gen_limit=40, convention=convention)
    ref = _check_resume_at_every_generation(g, config)
    assert ref.generations < config.gen_limit  # scenario sanity: early exit
    assert ref.grid.any()  # similarity exit, not the empty-grid exit
