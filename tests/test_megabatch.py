"""Resident mega-batch engine (ISSUE 6): the on-device serving loop.

Covers the three legs of the resident lane and its satellites:

- **batched temporal depth** — ``engine.make_batch_runner(temporal_depth=T)``
  byte-identical to the per-generation form for mixed-fate batches (dynamic
  per-board gen limits, empty/similar/gen_limit exits), both conventions,
  every depth in the tuned axis {1, 2, 4, 8};
- **the ring runner** — ``make_ring_runner``/``stage_ring``/``dispatch_ring``
  /``complete_ring`` bit-identical to the per-batch runner slot for slot,
  including partially filled rings and the donation-safe retry re-dispatch;
- **the resident serve lane** — ``Scheduler(resident_ring=R)`` results
  byte-identical to the classic depth-1 worker, exactly-once under SIGKILL
  mid-ring (real subprocess kill + journal replay), ring/thread hygiene
  after drain, and the no-re-pack retry contract
  (``engine_stage_packs_total``).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from gol_tpu import engine
from gol_tpu.config import GameConfig
from gol_tpu.io import text_grid
from gol_tpu.obs import recorder as obs_recorder, registry as obs_registry
from gol_tpu.serve import batcher
from gol_tpu.serve.jobs import DONE, JobJournal, new_job
from gol_tpu.serve.resident import STATE_PROVIDER, ResidentEngine
from gol_tpu.serve.scheduler import Scheduler

CONVENTIONS = ["c", "cuda"]


def _mixed_fate_boards():
    """Boards covering every exit reason inside one batch."""
    dies = np.zeros((32, 32), np.uint8)
    dies[4, 4] = 1  # lone cell: empty exit
    still = np.zeros((32, 32), np.uint8)
    still[3:5, 3:5] = 1  # block still life: similarity exit
    soup = text_grid.generate(32, 32, seed=7)  # runs to the limit
    soup2 = text_grid.generate(32, 32, seed=8)
    return [dies, still, soup, soup2]


def _solo(board, config):
    return engine.simulate(board, config)


def _assert_matches_solo(results, boards, configs):
    reasons = set()
    for r, board, config in zip(results, boards, configs):
        want = _solo(board, config)
        assert np.array_equal(r.grid, want.grid)
        assert r.generations == want.generations
        reasons.add(r.exit_reason)
    return reasons


def _wait(predicate, timeout=60.0, interval=0.01):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def _serve_threads():
    return [t.name for t in threading.enumerate()
            if t.name.startswith("gol-serve-")]


# ---------------------------------------------------------------------------
# Batched temporal depth.
# ---------------------------------------------------------------------------


class TestBatchedTemporalDepth:
    @pytest.mark.parametrize("convention", CONVENTIONS)
    @pytest.mark.parametrize("depth", [2, 4, 8])
    def test_bit_exact_with_mixed_fates(self, convention, depth):
        boards = _mixed_fate_boards()
        # Dynamic per-board limits: one board's limit lands mid-depth-block,
        # the case that would corrupt its grid if depth overran an exit.
        configs = [GameConfig(gen_limit=g, convention=convention)
                   for g in (60, 60, 13, 7)]
        results = engine.simulate_batch(
            boards, configs, padded_shape=(32, 32), pad_batch_to=4,
            temporal_depth=depth,
        )
        reasons = _assert_matches_solo(results, boards, configs)
        assert reasons == {"empty", "similar", "gen_limit"}

    def test_depth_validation(self):
        with pytest.raises(ValueError):
            engine.make_batch_runner((32, 32), 1, temporal_depth=0)
        with pytest.raises(ValueError):
            engine.make_batch_runner((32, 32), 1, temporal_depth=65)

    def test_depth1_is_the_default(self):
        """Absent a tuned plan the serve path stages at depth 1 — the pin
        that default behavior is byte-identical to pre-resident serving."""
        assert batcher._plan().temporal_depth == 1
        staged = engine.stage_batch(
            [np.zeros((32, 32), np.uint8)], GameConfig(gen_limit=2),
            padded_shape=(32, 32), pad_batch_to=1,
        )
        assert staged.temporal_depth == 1


# ---------------------------------------------------------------------------
# The ring runner.
# ---------------------------------------------------------------------------


class TestRingEngine:
    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_partial_ring_matches_batch_and_solo(self, convention):
        boards = _mixed_fate_boards()
        config = GameConfig(gen_limit=40, convention=convention)
        s1 = engine.stage_batch(boards[:2], config, padded_shape=(32, 32),
                                pad_batch_to=2)
        s2 = engine.stage_batch(boards[2:], config, padded_shape=(32, 32),
                                pad_batch_to=2)
        ring = engine.stage_ring([s1, s2], ring=4)  # 2 filled, 2 inert slots
        slots = engine.complete_ring(engine.dispatch_ring(ring))
        assert len(slots) == 2
        reasons = set()
        for slot, chunk in zip(slots, (boards[:2], boards[2:])):
            reasons |= _assert_matches_solo(slot, chunk, [config] * 2)
        assert reasons == {"empty", "similar", "gen_limit"}

    def test_masked_bucket_with_temporal_depth(self):
        rng = np.random.default_rng(3)
        boards = [rng.integers(0, 2, (20, 24), np.uint8),
                  rng.integers(0, 2, (30, 30), np.uint8)]
        config = GameConfig(gen_limit=25)
        staged = engine.stage_batch(boards, config, padded_shape=(32, 32),
                                    pad_batch_to=2, temporal_depth=4)
        assert staged.mode == "masked"
        ring = engine.stage_ring([staged], ring=2)
        (results,) = engine.complete_ring(engine.dispatch_ring(ring))
        _assert_matches_solo(results, boards, [config] * 2)

    def test_redispatch_same_ring_is_idempotent(self):
        """The retry path: a second dispatch from the retained host staging
        (the donated device buffers of the first are consumed) returns
        identical results — and never re-packs (the staging counter)."""
        boards = _mixed_fate_boards()
        config = GameConfig(gen_limit=30)
        staged = engine.stage_batch(boards, config, padded_shape=(32, 32),
                                    pad_batch_to=4)
        packs0 = obs_registry.default().counter("engine_stage_packs_total")
        ring = engine.stage_ring([staged], ring=2)
        first = engine.complete_ring(engine.dispatch_ring(ring))
        second = engine.complete_ring(engine.dispatch_ring(ring))
        for a, b in zip(first[0], second[0]):
            assert np.array_equal(a.grid, b.grid)
            assert a.generations == b.generations
            assert a.exit_reason == b.exit_reason
        assert obs_registry.default().counter(
            "engine_stage_packs_total") == packs0  # zero re-packs on retry

    def test_ring_rejects_mixed_geometry_and_overflow(self):
        config = GameConfig(gen_limit=5)
        a = engine.stage_batch([np.zeros((32, 32), np.uint8)], config,
                               padded_shape=(32, 32), pad_batch_to=1)
        b = engine.stage_batch([np.zeros((32, 32), np.uint8)] * 2, config,
                               padded_shape=(32, 32), pad_batch_to=2)
        with pytest.raises(ValueError):
            engine.stage_ring([a, b], ring=2)  # different batch rung
        cuda = engine.stage_batch(
            [np.zeros((32, 32), np.uint8)],
            GameConfig(gen_limit=5, convention="cuda"),
            padded_shape=(32, 32), pad_batch_to=1,
        )
        with pytest.raises(ValueError):
            engine.stage_ring([a, cuda], ring=2)  # different convention
        with pytest.raises(ValueError):
            engine.stage_ring([a, a, a], ring=2)  # overflow
        with pytest.raises(ValueError):
            engine.stage_ring([], ring=2)


# ---------------------------------------------------------------------------
# The resident serve lane.
# ---------------------------------------------------------------------------


class TestResidentServe:
    def test_validation(self):
        with pytest.raises(ValueError):
            Scheduler(resident_ring=1)
        with pytest.raises(ValueError):
            Scheduler(resident_ring=2)  # pipeline_depth defaults to 1
        with pytest.raises(ValueError):
            Scheduler(resident_ring=2, pipeline_depth=4,
                      run_batch=lambda key, jobs: [])  # no ring for injected

    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_results_match_classic_depth1(self, tmp_path, convention):
        """The acceptance pin: resident-lane results are byte-identical to
        the classic depth-1 worker for mixed-fate batches across two
        buckets — grids, generation counts, AND exit reasons."""
        boards = []
        for i in range(12):
            if i % 4 == 0:
                b = np.zeros((32, 32), np.uint8)
                b[2, 2] = 1  # empty exit
            elif i % 4 == 1:
                b = np.zeros((30, 30), np.uint8)
                b[3:5, 3:5] = 1  # still life in the masked bucket
            else:
                side = 32 if i % 2 == 0 else 30
                b = text_grid.generate(side, side, seed=900 + i)
            boards.append(b)

        def run(**kwargs):
            sched = Scheduler(flush_age=0.01, max_batch=4, **kwargs)
            jobs = [
                new_job(b.shape[1], b.shape[0], b, gen_limit=18,
                        convention=convention)
                for b in boards
            ]
            for job in jobs:
                sched.submit(job)
            sched.start()
            assert sched.drain(timeout=120)
            sched.stop(drain=False)
            assert all(j.state == DONE for j in jobs)
            return jobs

        classic = run()
        resident = run(pipeline_depth=8, resident_ring=4)
        for a, b in zip(classic, resident):
            assert np.array_equal(a.result.grid, b.result.grid)
            assert a.result.generations == b.result.generations
            assert a.result.exit_reason == b.result.exit_reason
        reasons = {j.result.exit_reason for j in resident}
        assert reasons == {"empty", "similar", "gen_limit"}

    def test_ring_and_thread_hygiene_after_drain(self, tmp_path):
        journal = JobJournal(str(tmp_path / "j"))
        sched = Scheduler(journal=journal, flush_age=0.0, max_batch=4,
                          pipeline_depth=4, resident_ring=2)
        jobs = [new_job(32, 32, text_grid.generate(32, 32, seed=40 + i),
                        gen_limit=8) for i in range(6)]
        for job in jobs:
            sched.submit(job)
        sched.start()
        assert sched.drain(timeout=120)
        stats = sched.stats()
        # Drained: every lane's open ring and unresolved drains are empty.
        assert all(v == 0 for k, v in stats["resident_rings"].items()
                   if k.endswith((".open", ".unresolved_drains")))
        assert any(k.endswith(".drains_total") and v > 0
                   for k, v in stats["resident_rings"].items())
        sched.stop(drain=False)
        assert _serve_threads() == []
        # The flight-recorder state provider is gone after stop.
        assert STATE_PROVIDER not in obs_recorder._state_providers
        replay = journal.replay()
        journal.close()
        assert not replay.pending
        assert set(replay.results) == {j.id for j in jobs}

    def test_state_provider_reports_ring_state(self):
        eng = ResidentEngine(ring=2)
        try:
            assert STATE_PROVIDER in obs_recorder._state_providers
            key = batcher.bucket_for(
                new_job(32, 32, np.zeros((32, 32), np.uint8), gen_limit=2)
            )
            staged = eng.stage(key, [
                new_job(32, 32, text_grid.generate(32, 32, seed=1),
                        gen_limit=4)
            ])
            ticket = eng.dispatch(staged)
            state = eng.state()
            # Eager policy: an idle lane dispatches the slot immediately
            # (the device must never wait on a fuller ring).
            assert state[f"{key.label()}.open"] == 0
            assert state[f"{key.label()}.unresolved_drains"] == 1
            results = eng.complete(ticket)
            assert len(results) == 1
            state = eng.state()
            assert state[f"{key.label()}.open"] == 0
            assert state[f"{key.label()}.unresolved_drains"] == 0
            assert state[f"{key.label()}.drains_total"] == 1
        finally:
            eng.close()
        assert STATE_PROVIDER not in obs_recorder._state_providers

    def test_worker_retry_reuses_retained_staging_no_repack(self):
        """The fixed satellite bug: the depth-1 worker used to re-run the
        whole stage (stack + np.packbits) on every retry attempt. Now it
        stages once and retries dispatch+complete from the retained host
        staging — pinned by the pack counter AND the stage call count."""
        calls = {"stage": 0, "dispatch": 0, "complete": 0}

        def stage(key, jobs):
            calls["stage"] += 1
            return batcher.stage(key, jobs)

        def dispatch(staged):
            calls["dispatch"] += 1
            return batcher.dispatch(staged)

        def complete(inflight):
            calls["complete"] += 1
            if calls["complete"] == 1:
                raise OSError("connection reset by peer")
            return batcher.complete(inflight)

        sched = Scheduler(flush_age=0.0,
                          split_batch=(stage, dispatch, complete))
        assert sched.pipeline_depth == 1  # the classic worker path
        job = new_job(32, 32, text_grid.generate(32, 32, seed=5), gen_limit=6)
        packs0 = obs_registry.default().counter("engine_stage_packs_total")
        sched.submit(job)
        sched.start()
        assert _wait(lambda: job.state == DONE), job.state
        sched.stop(drain=False)
        assert calls == {"stage": 1, "dispatch": 2, "complete": 2}
        assert obs_registry.default().counter(
            "engine_stage_packs_total") == packs0 + 1
        assert sched.metrics.counter("batch_retries_total") == 1

    def test_flight_dump_and_report_carry_ring_state(self, tmp_path):
        """The observability satellite end to end: a flight dump taken
        mid-session carries the ring state provider, and `gol trace-report`
        renders the resident span, the gap histogram, and the occupancy
        gauge."""
        from gol_tpu.obs import report as obs_report, trace as obs_trace

        obs_registry.reset_default()
        obs_trace.enable()
        obs_recorder.install(str(tmp_path))
        try:
            sched = Scheduler(flush_age=0.0, max_batch=2, pipeline_depth=4,
                              resident_ring=2)
            jobs = [new_job(32, 32, text_grid.generate(32, 32, seed=80 + i),
                            gen_limit=6) for i in range(4)]
            for job in jobs:
                sched.submit(job)
            sched.start()
            assert sched.drain(timeout=120)
            path = obs_recorder.trigger("test")
            sched.stop(drain=False)
        finally:
            obs_recorder.uninstall()
            obs_trace.disable()
        rendered = obs_report.render(path)
        assert "serve.resident_loop" in rendered
        assert "state[resident_rings]" in rendered
        assert "dispatch_gap_seconds" in rendered
        assert "ring_slot_occupancy" in rendered

    def test_resident_metrics_land_in_registry(self, tmp_path):
        obs_registry.reset_default()
        sched = Scheduler(flush_age=0.0, max_batch=2, pipeline_depth=4,
                          resident_ring=2)
        jobs = [new_job(32, 32, text_grid.generate(32, 32, seed=70 + i),
                        gen_limit=6) for i in range(4)]
        for job in jobs:
            sched.submit(job)
        sched.start()
        assert sched.drain(timeout=120)
        sched.stop(drain=False)
        snap = obs_registry.default().snapshot()
        assert "dispatch_gap_seconds" in snap["histograms"]
        assert "ring_slot_occupancy" in snap["gauges"]
        assert 0 < snap["gauges"]["ring_slot_occupancy"] <= 1


# ---------------------------------------------------------------------------
# Exactly-once under SIGKILL mid-ring (real subprocess + journal replay).
# ---------------------------------------------------------------------------


ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _start_resident_server(port: int, journal_dir: str) -> subprocess.Popen:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [
            sys.executable, "-m", "gol_tpu", "serve",
            "--port", str(port), "--journal-dir", journal_dir,
            "--flush-age", "0.001", "--max-batch", "4",
            "--pipeline-depth", "8", "--resident-ring", "4",
        ],
        env=env, cwd=ROOT,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


def _wait_serving(proc, url, timeout=120):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"server died rc={proc.returncode}: {proc.stdout.read()}"
            )
        try:
            code, _ = _http("GET", url + "/healthz", timeout=5)
            if code == 200:
                return
        except (urllib.error.URLError, OSError):
            time.sleep(0.05)
    raise RuntimeError("server did not come up")


class TestSigkillMidRing:
    def test_exactly_once_after_sigkill_and_replay(self, tmp_path):
        """SIGKILL a resident-ring server with drains in flight; the
        restarted server replays the journal and every accepted job ends
        DONE exactly once, byte-identical to solo runs."""
        journal_dir = str(tmp_path / "journal")
        njobs, gen_limit = 12, 400
        boards = [text_grid.generate(64, 64, seed=5000 + i)
                  for i in range(njobs)]
        payloads = [
            {
                "width": 64, "height": 64, "gen_limit": gen_limit,
                "cells": text_grid.encode(b).decode("ascii"),
            }
            for b in boards
        ]

        port = _free_port()
        url = f"http://127.0.0.1:{port}"
        proc = _start_resident_server(port, journal_dir)
        ids = []
        try:
            _wait_serving(proc, url)
            for payload in payloads:
                code, out = _http("POST", url + "/jobs", payload)
                assert code == 202, out
                ids.append(out["id"])
            # Give the ring a moment to get drains genuinely in flight,
            # then kill without any Python unwinding.
            time.sleep(0.4)
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()

        # Restart on the same journal: unfinished jobs replay and run.
        port2 = _free_port()
        url2 = f"http://127.0.0.1:{port2}"
        proc2 = _start_resident_server(port2, journal_dir)
        try:
            _wait_serving(proc2, url2)
            results = {}

            def all_done():
                for jid in ids:
                    if jid in results:
                        continue
                    code, out = _http("GET", f"{url2}/result/{jid}",
                                      timeout=30)
                    if code != 200:
                        return False
                    results[jid] = out
                return True

            assert _wait(all_done, timeout=240), (
                f"unfinished: {set(ids) - set(results)}"
            )
        finally:
            if proc2.poll() is None:
                proc2.send_signal(signal.SIGTERM)
                try:
                    proc2.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    proc2.kill()

        # Byte-identical to solo runs (the engine contract survived the
        # kill), and the journal shows each id DONE exactly once.
        for jid, board in zip(ids, boards):
            out = results[jid]
            want = engine.simulate(board, GameConfig(gen_limit=gen_limit))
            got = text_grid.decode(out["grid"].encode("ascii"), 64, 64)
            assert np.array_equal(got, want.grid)
            assert out["generations"] == want.generations
        with open(os.path.join(journal_dir, JobJournal.FILENAME), "rb") as f:
            events = [json.loads(line)
                      for line in f.read().splitlines() if line]
        for jid in ids:
            dones = [e for e in events
                     if e.get("event") == "done" and e.get("id") == jid]
            assert len(dones) == 1, f"{jid} done {len(dones)} times"
