"""Async execution pipeline (ISSUE 5): overlap host work with device compute.

Covers the three legs of gol_tpu/pipeline:

- the async checkpoint writer: byte-compatibility with the sync path
  (outputs AND payloads), deferred-commit crash semantics, error
  propagation one boundary late, thread hygiene on both exit paths;
- the pipelined serve dispatch (``pipeline_depth`` >= 2): exactly-once
  results, retry, failure terminality, drain, thread hygiene;
- the engine's staged batch split and the donation compat shim.
"""

import os
import threading

import numpy as np
import pytest

from gol_tpu import cli, engine
from gol_tpu.config import GameConfig
from gol_tpu.io import text_grid
from gol_tpu.obs import recorder, registry as obs_registry
from gol_tpu.pipeline.inflight import Handoff
from gol_tpu.pipeline.snapshot import HostSnapshot
from gol_tpu.pipeline.writer import AsyncCheckpointWriter
from gol_tpu.resilience import faults
from gol_tpu.resilience.checkpoint import CheckpointManager, PayloadCodec
from gol_tpu.resilience.faults import InjectedCrash
from gol_tpu.serve import batcher
from gol_tpu.serve.jobs import DONE, FAILED, JobJournal, new_job
from gol_tpu.serve.scheduler import Scheduler


@pytest.fixture(autouse=True)
def _disarmed():
    faults.clear()
    yield
    faults.clear()


def _pipeline_threads():
    """Threads this PR's machinery creates (writer + serve pipeline)."""
    return [
        t.name for t in threading.enumerate()
        if t.name.startswith(("gol-ckpt-writer", "gol-serve-"))
    ]


GEN_LIMIT = 12
EVERY = 3


def _run(capsys, args):
    capsys.readouterr()
    rc = cli.main(args)
    return rc, capsys.readouterr()


def _args(infile, out, ckdir, *extra):
    return [
        "16", "16", str(infile), "--variant", "game",
        "--gen-limit", str(GEN_LIMIT),
        "--checkpoint-every", str(EVERY),
        "--checkpoint-dir", str(ckdir),
        "--output", str(out),
        *extra,
    ]


@pytest.fixture
def grid16(tmp_path):
    p = tmp_path / "in.txt"
    text_grid.write_grid(str(p), text_grid.generate(16, 16, seed=77))
    return str(p)


class TestAsyncWriterCLI:
    def test_async_and_sync_byte_identical(self, tmp_path, grid16, capsys):
        """The acceptance pin: async (default) and --sync-checkpoints runs
        produce bit-identical final grids AND checkpoint payloads."""
        ref = tmp_path / "ref.out"
        rc, cap = _run(capsys, [
            "16", "16", grid16, "--variant", "game",
            "--gen-limit", str(GEN_LIMIT), "--output", str(ref)])
        assert rc == 0
        ref_gens = [l for l in cap.out.splitlines() if l.startswith("Generations")]

        outs, dirs, gens = {}, {}, {}
        for mode, extra in (("async", ()), ("sync", ("--sync-checkpoints",))):
            out = tmp_path / f"{mode}.out"
            ck = tmp_path / f"ck-{mode}"
            rc, cap = _run(capsys, _args(
                grid16, out, ck, "--checkpoint-keep", "8", *extra))
            assert rc == 0
            outs[mode] = out.read_bytes()
            dirs[mode] = ck
            gens[mode] = [l for l in cap.out.splitlines()
                          if l.startswith("Generations")]
        assert outs["async"] == outs["sync"] == ref.read_bytes()
        assert gens["async"] == gens["sync"] == ref_gens
        payloads = sorted(
            n for n in os.listdir(dirs["sync"]) if n.endswith(".out"))
        assert payloads  # the run actually checkpointed
        assert payloads == sorted(
            n for n in os.listdir(dirs["async"]) if n.endswith(".out"))
        for name in payloads:
            assert (dirs["async"] / name).read_bytes() == \
                (dirs["sync"] / name).read_bytes()

    def test_no_thread_leak_clean_run(self, tmp_path, grid16, capsys):
        rc, _ = _run(capsys, _args(grid16, tmp_path / "o.out", tmp_path / "ck"))
        assert rc == 0
        assert _pipeline_threads() == []

    def test_no_thread_leak_error_path(self, tmp_path, grid16):
        """join-on-exit also when the run crashes mid-loop (the writer's
        close() runs in the segment loop's finally)."""
        with pytest.raises(InjectedCrash):
            cli.main(_args(grid16, tmp_path / "o.out", tmp_path / "ck",
                           "--fault-plan", "kill_at_gen=6"))
        assert _pipeline_threads() == []

    def test_background_write_failure_surfaces_one_boundary_late(
        self, tmp_path, grid16, capsys
    ):
        """An injected hard write fault in the background writer aborts the
        run (rc 1, the CLI error contract) with the torn checkpoint
        invisible and the previous one committed — the deferred MPI_Wait
        status of the reference's async variant."""
        ck = tmp_path / "ck"
        rc, cap = _run(capsys, _args(
            grid16, tmp_path / "o.out", ck, "--fault-plan",
            "payload_write_fail=2"))
        assert rc == 1
        assert "injected" in cap.err
        names = os.listdir(ck)
        assert "ckpt-00000003.manifest.json" in names
        assert "ckpt-00000006.manifest.json" not in names
        assert _pipeline_threads() == []

    def test_writer_queue_metrics_and_hidden_time(self, tmp_path, grid16,
                                                  capsys):
        obs_registry.reset_default()
        rc, _ = _run(capsys, _args(grid16, tmp_path / "o.out", tmp_path / "ck"))
        assert rc == 0
        reg = obs_registry.default()
        assert reg.counter("checkpoint_saves_total") == 3  # gens 3, 6, 9
        assert reg.counter("checkpoint_write_hidden_seconds") >= 0
        snap = reg.snapshot()
        assert snap["gauges"].get("ckpt_writer_queue_depth") == 0


class TestAsyncWriterUnit:
    def _mgr(self, tmp_path, n=16, **kwargs):
        return CheckpointManager(
            str(tmp_path / "ck"), height=n, width=n,
            codec=PayloadCodec(
                format="text-grid", suffix=".out",
                write=lambda p, s: text_grid.write_grid(
                    p, np.asarray(s, dtype=np.uint8)),
                read=lambda p: text_grid.read_grid(p, n, n),
            ),
            **kwargs,
        )

    def test_commit_is_deferred_to_drain(self, tmp_path):
        """After save() returns, the checkpoint must NOT exist yet (its
        manifest commits at the next boundary/drain) — the write-ahead
        contract is literally 'not committed until the deferred wait'."""
        mgr = self._mgr(tmp_path)
        writer = AsyncCheckpointWriter(mgr)
        try:
            state = text_grid.generate(16, 16, seed=1)
            writer.save(state, 3, 0)
            # The payload write may or may not have finished; the MANIFEST
            # must not exist until drain() commits it.
            assert not os.path.exists(
                str(tmp_path / "ck" / "ckpt-00000003.manifest.json"))
            writer.drain()
            assert os.path.exists(
                str(tmp_path / "ck" / "ckpt-00000003.manifest.json"))
            restored = mgr.restore()
            assert restored is not None
            got, info = restored
            assert info.generation == 3
            assert np.array_equal(np.asarray(got, dtype=np.uint8), state)
        finally:
            writer.close()
        assert _pipeline_threads() == []

    def test_flight_recorder_dump_carries_writer_state(self, tmp_path):
        mgr = self._mgr(tmp_path)
        writer = AsyncCheckpointWriter(mgr)
        recorder.install(str(tmp_path / "flight"))
        try:
            writer.save(text_grid.generate(16, 16, seed=2), 3, 0)
            path = recorder.trigger("test")
            records = recorder.read_dump(path)
            states = [r for r in records if r.get("record") == "state"]
            assert any(r.get("name") == "checkpoint_writer" for r in states)
            (state,) = [r for r in states if r["name"] == "checkpoint_writer"]
            assert state["pending_generation"] in (None, 3)
            writer.drain()
        finally:
            writer.close()
            recorder.uninstall()
        # close() unregisters the provider: later dumps drop the entry.
        path = recorder.trigger("after-close")
        assert path is None  # unarmed now

    def test_double_save_skips_already_committed(self, tmp_path):
        """A resumed run re-reaching a committed boundary must not rewrite
        it (the sync path's `already` rule, preserved across the split)."""
        obs_registry.reset_default()
        mgr = self._mgr(tmp_path)
        state = text_grid.generate(16, 16, seed=3)
        writer = AsyncCheckpointWriter(mgr)
        try:
            writer.save(state, 3, 0)
            writer.drain()
            manifest = tmp_path / "ck" / "ckpt-00000003.manifest.json"
            before = manifest.read_bytes()
            writer.save(state, 3, 0)  # same boundary again
            writer.drain()
            assert manifest.read_bytes() == before
            # The skip counts as a completed save, like the sync lane's
            # unconditional wrapper increment — A/B metrics parity.
            reg = obs_registry.default()
            assert reg.counter("checkpoint_saves_total") == 2
        finally:
            writer.close()


class TestHostSnapshot:
    def test_payloads_and_checksums_match_device_writes(self, tmp_path):
        """A HostSnapshot must be indistinguishable from the live array to
        the payload writers and the CRC pass (the byte-compat keystone)."""
        import jax.numpy as jnp

        from gol_tpu.resilience.checkpoint import _shard_checksums

        grid = text_grid.generate(32, 32, seed=4)
        device = jnp.asarray(grid)
        snap = HostSnapshot(device)
        assert snap.shape == (32, 32)
        a, b = tmp_path / "a.out", tmp_path / "b.out"
        text_grid.write_grid(str(a), np.asarray(device, dtype=np.uint8))
        text_grid.write_grid(str(b), np.asarray(snap, dtype=np.uint8))
        assert a.read_bytes() == b.read_bytes()
        assert _shard_checksums(device) == _shard_checksums(snap)

    def test_sharded_array_mirrors_shards(self):
        import jax

        from gol_tpu.parallel.mesh import grid_sharding, make_mesh

        if len(jax.devices()) < 2:
            pytest.skip("needs >1 device")
        mesh = make_mesh(2, 1)
        grid = text_grid.generate(32, 32, seed=5)
        device = jax.device_put(grid, grid_sharding(mesh))
        snap = HostSnapshot(device)
        assert len(snap.addressable_shards) == len(
            list(device.addressable_shards))
        assert np.array_equal(np.asarray(snap), grid)
        from gol_tpu.resilience.checkpoint import _shard_checksums

        assert _shard_checksums(device) == _shard_checksums(snap)


class TestEngineBatchSplit:
    @pytest.mark.parametrize("convention", ["c", "cuda"])
    def test_staged_split_equals_simulate_batch(self, convention):
        boards = [text_grid.generate(24, 24, seed=s) for s in (1, 2, 3)]
        cfg = GameConfig(gen_limit=16, convention=convention)
        want = engine.simulate_batch(boards, cfg, padded_shape=(32, 32),
                                     pad_batch_to=4)
        staged = engine.stage_batch(boards, cfg, padded_shape=(32, 32),
                                    pad_batch_to=4)
        got = engine.complete_batch(engine.dispatch_batch(staged))
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert np.array_equal(g.grid, w.grid)
            assert g.generations == w.generations
            assert g.exit_reason == w.exit_reason

    def test_redispatch_same_staging_is_idempotent(self):
        """The retry contract: dispatching one staging twice gives the same
        results (host operands are retained; the device buffer is rebuilt)."""
        boards = [text_grid.generate(32, 32, seed=9)]
        staged = engine.stage_batch(boards, GameConfig(gen_limit=8))
        first = engine.complete_batch(engine.dispatch_batch(staged))
        second = engine.complete_batch(engine.dispatch_batch(staged))
        assert np.array_equal(first[0].grid, second[0].grid)
        assert first[0].generations == second[0].generations

    def test_empty_stage_is_none(self):
        assert engine.stage_batch([], GameConfig()) is None


class TestDonationShim:
    def test_cpu_backend_gets_plain_jit(self, monkeypatch):
        from gol_tpu.ops import jit_compat

        monkeypatch.setattr(jit_compat, "supports_donation", lambda: False)
        fn = jit_compat.jit_donating(lambda x: x + 1)
        assert int(fn(np.int32(1))) == 2

    def test_donating_backend_requests_donation(self, monkeypatch):
        from gol_tpu.ops import jit_compat

        captured = {}

        def fake_jit(fn, donate_argnums=None):
            captured["donate"] = donate_argnums
            return fn

        monkeypatch.setattr(jit_compat, "supports_donation", lambda: True)
        monkeypatch.setattr(jit_compat.jax, "jit", fake_jit)
        jit_compat.jit_donating(lambda x: x, donate_argnums=(0,))
        assert captured["donate"] == (0,)

    def test_segment_runner_values_unchanged(self):
        """Donation (or its absence) never changes values: the segmented
        loop still equals the unsegmented one."""
        grid = text_grid.generate(16, 16, seed=11)
        cfg = GameConfig(gen_limit=10)
        solo = engine.simulate(grid, cfg)
        last = None
        for gens, state, stopped in engine.simulate_segments(grid, cfg, None,
                                                             "auto", 3):
            last = (gens, np.asarray(state, dtype=np.uint8))
        assert last[0] == solo.generations
        assert np.array_equal(last[1], solo.grid)


class TestHandoff:
    def test_fifo_and_close(self):
        h = Handoff()
        h.put(1)
        h.put(2)
        assert h.get() == 1
        h.close()
        assert h.get() == 2  # close drains before the sentinel
        assert h.get() is None
        with pytest.raises(RuntimeError):
            h.put(3)

    def test_get_blocks_until_put(self):
        h = Handoff()
        got = []

        def consumer():
            got.append(h.get())

        t = threading.Thread(target=consumer)
        t.start()
        h.put("x")
        t.join(timeout=5)
        assert got == ["x"]


class TestPipelinedScheduler:
    def test_depth_validation(self):
        with pytest.raises(ValueError):
            Scheduler(pipeline_depth=0)
        with pytest.raises(ValueError):
            Scheduler(pipeline_depth=2, max_inflight=2)

    def test_depth2_end_to_end_exactly_once(self, tmp_path):
        journal = JobJournal(str(tmp_path / "j"))
        sched = Scheduler(journal=journal, flush_age=0.01, max_batch=4,
                          pipeline_depth=2)
        jobs = []
        for i in range(10):
            side = 32 if i % 2 == 0 else 30  # two buckets
            board = text_grid.generate(side, side, seed=600 + i)
            job = new_job(side, side, board, gen_limit=12)
            jobs.append((job, board))
            sched.submit(job)
        sched.start()
        assert sched.drain(timeout=120)
        sched.stop(drain=False)
        assert _pipeline_threads() == []
        for job, board in jobs:
            assert job.state == DONE
            solo = engine.simulate(board, GameConfig(gen_limit=12))
            assert np.array_equal(job.result.grid, solo.grid)
            assert job.result.generations == solo.generations
        replay = journal.replay()
        journal.close()
        assert not replay.pending
        assert set(replay.results) == {job.id for job, _ in jobs}
        assert sched.metrics.counter("jobs_completed_total") == 10
        assert sched.stats()["inflight_batches"] == 0

    def test_depth2_transient_error_retries(self):
        calls = {"n": 0}

        def flaky(key, batch):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("connection reset by peer")
            return batcher.run_batch(key, batch)

        sched = Scheduler(flush_age=0.0, pipeline_depth=2, run_batch=flaky)
        job = new_job(32, 32, text_grid.generate(32, 32, seed=13), gen_limit=5)
        sched.submit(job)
        sched.start()
        assert sched.drain(timeout=60)
        sched.stop(drain=False)
        assert job.state == DONE
        assert calls["n"] == 2
        assert sched.metrics.counter("batch_retries_total") == 1

    def test_depth2_retry_redispatches_from_retained_staging(self):
        """A transient completion failure retries dispatch+complete from
        the flight's RETAINED host staging: stage() runs once, dispatch()
        twice — the documented no-re-staging retry contract."""
        calls = {"stage": 0, "dispatch": 0, "complete": 0}

        def stage(key, batch):
            calls["stage"] += 1
            return batcher.stage(key, batch)

        def dispatch(staged):
            calls["dispatch"] += 1
            return batcher.dispatch(staged)

        def complete(inflight):
            calls["complete"] += 1
            if calls["complete"] == 1:
                raise OSError("connection reset by peer")
            return batcher.complete(inflight)

        sched = Scheduler(flush_age=0.0, pipeline_depth=2,
                          split_batch=(stage, dispatch, complete))
        job = new_job(32, 32, text_grid.generate(32, 32, seed=21), gen_limit=5)
        sched.submit(job)
        sched.start()
        assert sched.drain(timeout=60)
        sched.stop(drain=False)
        assert job.state == DONE
        assert calls == {"stage": 1, "dispatch": 2, "complete": 2}
        assert sched.metrics.counter("batch_retries_total") == 1

    def test_depth2_persistent_error_fails_jobs(self, tmp_path):
        def broken(key, batch):
            raise RuntimeError("device on fire")

        journal = JobJournal(str(tmp_path / "j"))
        sched = Scheduler(journal=journal, flush_age=0.0, pipeline_depth=2,
                          run_batch=broken)
        job = new_job(32, 32, text_grid.generate(32, 32, seed=14), gen_limit=5)
        sched.submit(job)
        sched.start()
        assert sched.drain(timeout=60)
        sched.stop(drain=False)
        assert job.state == FAILED
        assert "device on fire" in job.error
        replay = journal.replay()
        journal.close()
        assert job.id in replay.failed
        assert _pipeline_threads() == []

    def test_depth2_dispatch_stage_error_fails_jobs(self):
        """A failure inside the pipelined stage/dispatch is carried to the
        completer and classified by the SAME retry policy (here: hard)."""
        def bad_stage(key, batch):
            raise RuntimeError("stage exploded")

        sched = Scheduler(
            flush_age=0.0, pipeline_depth=2,
            split_batch=(bad_stage, batcher.dispatch, batcher.complete),
            run_batch=lambda key, batch: (_ for _ in ()).throw(
                RuntimeError("stage exploded")),
        )
        job = new_job(32, 32, text_grid.generate(32, 32, seed=15), gen_limit=5)
        sched.submit(job)
        sched.start()
        assert sched.drain(timeout=60)
        sched.stop(drain=False)
        assert job.state == FAILED

    def test_depth1_unchanged_default(self):
        """Absent the new knob the scheduler is the classic worker pool —
        no pipeline threads, no window (the observable-behavior pin)."""
        sched = Scheduler()
        assert sched.pipeline_depth == 1
        sched.start()
        names = [t.name for t in sched._threads]
        assert names == ["gol-serve-worker-0"]
        assert sched._window is None
        sched.stop(drain=False)


class TestKillDuringCkptWrite:
    """The new fault: SIGKILL/crash while the background writer is
    mid-payload-write. (The CLI-level byte-identical auto-resume proof for
    both exit paths lives in tests/test_crash_recovery.py; the real-SIGKILL
    subprocess version is tools/pipeline_smoke.py.)"""

    def test_parse_and_fire(self, tmp_path):
        plan = faults.FaultPlan.parse("kill_during_ckpt_write=1")
        faults.install(plan)
        p = tmp_path / "payload.out"
        p.write_bytes(b"x" * 100)
        with pytest.raises(InjectedCrash):
            faults.on_payload_write(str(p))
        assert p.stat().st_size == 50  # torn mid-file first
        # one-shot: later writes proceed
        faults.on_payload_write(str(p))

    def test_unknown_key_still_loud(self):
        with pytest.raises(ValueError):
            faults.FaultPlan.parse("kill_during_ckpt_writ=1")
