"""Known-pattern and determinism tests (SURVEY.md §4d, §5 race-detection).

The reference's manual race avoidance (odd/even MPI request sets,
cudaDeviceSynchronize discipline) is replaced by XLA's functional model;
determinism tests assert the property the reference only hoped for: same
input -> same output bytes, every time, on every kernel and topology.
"""

import numpy as np
import pytest

from gol_tpu import engine, oracle
from gol_tpu.config import GameConfig
from gol_tpu.parallel.mesh import make_mesh

BLINKER = np.array([[1, 1, 1]], np.uint8)
PULSAR_QUADRANT = [
    "..###",
    ".....",
    "#....",
    "#....",
    "#....",
    "..###",
]
LWSS = np.array(
    [
        [0, 1, 1, 1, 1],
        [1, 0, 0, 0, 1],
        [0, 0, 0, 0, 1],
        [1, 0, 0, 1, 0],
    ],
    np.uint8,
)
R_PENTOMINO = np.array([[0, 1, 1], [1, 1, 0], [0, 1, 0]], np.uint8)


def _place(height, width, pattern, at):
    g = np.zeros((height, width), np.uint8)
    r, c = at
    g[r : r + pattern.shape[0], c : c + pattern.shape[1]] = pattern
    return g


def test_blinker_period_two():
    g = _place(16, 32, BLINKER, (8, 8))
    one = oracle.evolve(g)
    two = oracle.evolve(one)
    assert not np.array_equal(one, g)
    np.testing.assert_array_equal(two, g)
    # Oscillators never trigger the similarity (fixed-point) exit.
    res = engine.simulate(g, GameConfig(gen_limit=30))
    assert res.generations == 30


def test_lwss_translates():
    """A lightweight spaceship translates 2 cells every 4 generations."""
    g = _place(32, 64, LWSS, (12, 30))
    four = g
    for _ in range(4):
        four = oracle.evolve(four)
    shifted = [np.roll(g, s, axis=a) for a in (0, 1) for s in (2, -2)]
    assert any(np.array_equal(four, s) for s in shifted)
    assert four.sum() == g.sum()  # still a 9-cell ship, not debris


@pytest.mark.parametrize("kernel", ["lax", "packed"])
def test_r_pentomino_long_run(kernel):
    """Chaotic growth for 300 generations, engine vs oracle, torus wrap hit."""
    g = _place(64, 64, R_PENTOMINO, (30, 30))
    config = GameConfig(gen_limit=300)
    expect = oracle.run(g, config)
    got = engine.simulate(g, config, kernel=kernel)
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations


@pytest.mark.parametrize("kernel", ["lax", "packed"])
def test_rectangular_grids(kernel):
    rng = np.random.default_rng(31)
    g = rng.integers(0, 2, size=(16, 96), dtype=np.uint8)
    config = GameConfig(gen_limit=50)
    expect = oracle.run(g, config)
    got = engine.simulate(g, config, kernel=kernel)
    np.testing.assert_array_equal(got.grid, expect.grid)


@pytest.mark.parametrize(
    "kernel,mesh_shape", [("lax", None), ("packed", None), ("packed", (2, 4))]
)
def test_determinism(kernel, mesh_shape):
    """Same input -> same output bytes, run twice (SURVEY.md §5)."""
    mesh = make_mesh(*mesh_shape) if mesh_shape else None
    rng = np.random.default_rng(37)
    g = rng.integers(0, 2, size=(32, 128), dtype=np.uint8)
    config = GameConfig(gen_limit=40)
    a = engine.simulate(g, config, mesh=mesh, kernel=kernel)
    b = engine.simulate(g, config, mesh=mesh, kernel=kernel)
    np.testing.assert_array_equal(a.grid, b.grid)
    assert a.generations == b.generations
