"""Sparse tiled engine (gol_tpu/sparse) + RLE codec (io/rle) tests.

The acceptance surface of ISSUE 12:

- tile activation/elision correctness at every tile boundary (the glider
  crossing a tile corner is the canonical trap);
- sparse-vs-dense byte-identity — cells, generation count, exit reason —
  on overlapping shapes for BOTH conventions, all three exit reasons;
- occupancy-index replay through the journal machinery (a replayed
  sparse job re-runs from its RLE spec to an identical result);
- tile-memo hits byte-identical to memo-disabled runs;
- RLE round-trips and golden patterns.
"""

import json
import time

import numpy as np
import pytest

from gol_tpu import engine, oracle
from gol_tpu.config import Convention, GameConfig
from gol_tpu.io import rle
from gol_tpu.serve import batcher
from gol_tpu.serve.jobs import DONE, Job, JobJournal, JobResult, new_job
from gol_tpu.serve.scheduler import Scheduler
from gol_tpu.sparse import (
    SparseBoard,
    TileMemo,
    auto_engine,
    dense_cells_guard,
    simulate_sparse,
)
from gol_tpu.sparse import engine as sparse_engine

GLIDER_RLE = "x = 3, y = 3, rule = B3/S23\nbob$2bo$3o!"
GLIDER = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.uint8)

GOSPER_RLE = """#N Gosper glider gun
x = 36, y = 9, rule = B3/S23
24bo$22bobo$12b2o6b2o12b2o$11bo3bo4b2o12b2o$2o8bo5bo3b2o$2o8bo3bob2o4b
obo$10bo5bo7bo$11bo3bo$12b2o!"""

CONVENTIONS = (Convention.C, Convention.CUDA)


def _assert_matches_dense(grid, config, tile, memo=None):
    """The byte-gate: sparse vs oracle AND vs the dense engine — cells,
    generation count, and (via the engine's batch lane) exit reason."""
    ref = oracle.run(grid.copy(), config)
    board = SparseBoard.from_dense(grid, tile)
    result = simulate_sparse(board, config, memo)
    assert result.generations == ref.generations
    assert np.array_equal(result.board.to_dense(), ref.grid)
    # Exit reason against the batched engine's per-board classification.
    [batch] = engine.simulate_batch([grid.copy()], [config])
    assert result.exit_reason == batch.exit_reason
    assert result.generations == batch.generations
    assert np.array_equal(result.board.to_dense(), batch.grid)
    return result


# ---------------------------------------------------------------------------
# RLE codec
# ---------------------------------------------------------------------------


class TestRle:
    def test_glider_golden(self):
        assert np.array_equal(rle.parse(GLIDER_RLE), GLIDER)

    def test_gosper_gun_golden(self):
        gun = rle.parse(GOSPER_RLE)
        assert gun.shape == (9, 36)
        assert int(gun.sum()) == 36

    def test_r_pentomino_golden(self):
        pent = rle.parse("x = 3, y = 3, rule = B3/S23\nb2o$2o$bo!")
        assert np.array_equal(
            pent, np.array([[0, 1, 1], [1, 1, 0], [0, 1, 0]], np.uint8)
        )

    def test_round_trip_random(self):
        rng = np.random.default_rng(3)
        for _ in range(10):
            h, w = rng.integers(1, 40, size=2)
            grid = (rng.random((h, w)) < 0.3).astype(np.uint8)
            assert np.array_equal(rle.parse(rle.encode(grid)), grid)

    def test_round_trip_empty_and_full(self):
        for grid in (np.zeros((5, 7), np.uint8), np.ones((5, 7), np.uint8)):
            assert np.array_equal(rle.parse(rle.encode(grid)), grid)

    def test_missing_count_means_one_and_short_rows_pad(self):
        grid = rle.parse("x = 4, y = 2, rule = B3/S23\no$2bo!")
        assert np.array_equal(
            grid, np.array([[1, 0, 0, 0], [0, 0, 1, 0]], np.uint8)
        )

    def test_non_b3s23_rule_rejected(self):
        with pytest.raises(ValueError, match="B3/S23"):
            rle.parse("x = 3, y = 3, rule = B36/S23\n3o!")

    def test_legacy_rule_spelling_accepted(self):
        assert rle.parse("x = 1, y = 1, rule = 23/3\no!").sum() == 1

    def test_overrun_rejected(self):
        with pytest.raises(ValueError, match="overruns"):
            rle.parse("x = 2, y = 1, rule = B3/S23\n3o!")
        with pytest.raises(ValueError, match="overruns"):
            rle.parse("x = 3, y = 1, rule = B3/S23\n3o$3o!")

    def test_garbage_token_rejected(self):
        with pytest.raises(ValueError, match="malformed"):
            rle.parse("x = 3, y = 1, rule = B3/S23\n3;!")

    def test_missing_header_rejected(self):
        with pytest.raises(ValueError, match="header"):
            rle.parse("#C no header here\n")

    def test_dense_parse_cap(self):
        with pytest.raises(ValueError, match="cap"):
            rle.parse("x = 100000, y = 100000, rule = B3/S23\no!")

    def test_line_wrap_under_70_columns(self):
        rng = np.random.default_rng(9)
        grid = (rng.random((60, 60)) < 0.5).astype(np.uint8)
        text = rle.encode(grid)
        assert all(len(line) <= 70 for line in text.splitlines())
        assert np.array_equal(rle.parse(text), grid)


# ---------------------------------------------------------------------------
# SparseBoard
# ---------------------------------------------------------------------------


class TestSparseBoard:
    def test_from_dense_round_trip(self):
        rng = np.random.default_rng(1)
        grid = (rng.random((24, 32)) < 0.3).astype(np.uint8)
        board = SparseBoard.from_dense(grid, tile=8)
        assert np.array_equal(board.to_dense(), grid)

    def test_dead_tiles_elided(self):
        grid = np.zeros((32, 32), np.uint8)
        grid[0, 0] = 1  # one live cell -> one live tile
        board = SparseBoard.from_dense(grid, tile=8)
        assert board.live_tiles == 1
        assert board.occupancy() == 1 / 16
        assert board.population() == 1

    def test_invariant_no_dead_tiles_stored(self):
        board = SparseBoard(32, 32, 8)
        board.set_tile((1, 1), np.zeros((8, 8), np.uint8))
        assert board.live_tiles == 0

    def test_place_spans_tile_boundaries(self):
        board = SparseBoard(32, 32, 8)
        board.place(GLIDER, 6, 6)  # straddles 4 tiles at the 8x8 corner
        assert board.live_tiles == 4
        dense = np.zeros((32, 32), np.uint8)
        dense[6:9, 6:9] = GLIDER
        assert np.array_equal(board.to_dense(), dense)

    def test_place_out_of_bounds_rejected(self):
        board = SparseBoard(16, 16, 8)
        with pytest.raises(ValueError, match="does not fit"):
            board.place(GLIDER, 14, 0)

    def test_indivisible_universe_rejected(self):
        with pytest.raises(ValueError, match="divide"):
            SparseBoard(30, 32, 8)

    def test_rle_round_trip_sparse(self):
        board = SparseBoard(64, 64, 8)
        board.place(rle.parse(GOSPER_RLE), 10, 20)
        board.place(GLIDER, 50, 3)
        again = SparseBoard.from_rle(board.to_rle(), 64, 64, 8)
        assert again == board

    def test_giant_universe_never_dense(self):
        board = SparseBoard.from_pattern(GLIDER, 60000, 60000,
                                         1 << 16, 1 << 16, 256)
        assert board.population() == 5
        assert board.live_tiles <= 4
        text = board.to_rle()
        assert SparseBoard.from_rle(text, 1 << 16, 1 << 16, 256) == board
        with pytest.raises(ValueError, match="ceiling"):
            board.to_dense()

    def test_from_rle_content_must_fit_universe(self):
        """Review regression: explicit universe extents smaller than the
        RLE header's must reject, never write phantom out-of-grid tiles."""
        doc = "x = 16, y = 16, rule = B3/S23\n12$12bo!"
        with pytest.raises(ValueError, match="does not fit"):
            SparseBoard.from_rle(doc, 8, 8, 4)
        with pytest.raises(ValueError, match="does not fit"):
            SparseBoard.from_rle(GLIDER_RLE, 8, 8, 4, x=7)

    def test_memory_lru_byte_bound(self):
        """Review regression: the tile memo's memory tier is byte-bounded
        (an entry count alone is no memory bound when entries are 64 KB
        tile interiors)."""
        from gol_tpu.cache.store import CacheEntry, MemoryLRU

        lru = MemoryLRU(max_entries=1000, max_bytes=300)
        for i in range(10):
            lru.put(f"k{i}", CacheEntry(
                grid=np.zeros((10, 10), np.uint8),  # 100 bytes each
                generations=0, exit_reason="tile",
            ))
        assert lru.grid_bytes <= 300
        assert len(lru) == 3
        assert lru.get("k9") is not None  # newest survive
        assert lru.get("k0") is None
        lru.pop("k9")
        assert lru.grid_bytes == 200

    def test_dense_cells_guard_message(self):
        with pytest.raises(ValueError, match="sparse lane"):
            dense_cells_guard(1 << 16, 1 << 16)
        dense_cells_guard(1024, 1024)  # small boards pass


# ---------------------------------------------------------------------------
# Sparse engine: byte-identity vs dense on overlapping shapes
# ---------------------------------------------------------------------------


class TestSparseEngine:
    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_glider_crosses_tile_corner(self, convention):
        """The canonical trap: a glider's leading cell touches a tile
        corner, so the diagonal neighbor must activate through the corner
        halo cell. 300 generations crosses every 8-cell boundary of a
        64x64 universe many times (with toroidal wrap)."""
        grid = np.zeros((64, 64), np.uint8)
        grid[1:4, 1:4] = GLIDER
        cfg = GameConfig(gen_limit=300, convention=convention)
        result = _assert_matches_dense(grid, cfg, tile=8)
        assert result.exit_reason == "gen_limit"

    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_all_three_exit_reasons(self, convention):
        # gen_limit: a glider never stabilizes
        g = np.zeros((32, 32), np.uint8)
        g[1:4, 1:4] = GLIDER
        r = _assert_matches_dense(
            g, GameConfig(gen_limit=40, convention=convention), tile=8)
        assert r.exit_reason == "gen_limit"
        # similar: a still-life block
        g = np.zeros((16, 16), np.uint8)
        g[4:6, 4:6] = 1
        r = _assert_matches_dense(
            g, GameConfig(gen_limit=40, convention=convention), tile=8)
        assert r.exit_reason == "similar"
        # empty: a lone cell dies
        g = np.zeros((16, 16), np.uint8)
        g[3, 3] = 1
        r = _assert_matches_dense(
            g, GameConfig(gen_limit=40, convention=convention), tile=8)
        assert r.exit_reason == "empty"

    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_soup_byte_identity(self, convention):
        rng = np.random.default_rng(11)
        grid = (rng.random((24, 24)) < 0.4).astype(np.uint8)
        _assert_matches_dense(
            grid, GameConfig(gen_limit=60, convention=convention), tile=8)

    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_single_tile_universe_self_wraps(self, convention):
        """A one-tile universe's halo wraps onto itself — the tile-grid
        torus degenerates to the dense torus exactly."""
        grid = np.zeros((8, 8), np.uint8)
        grid[0:3, 0:3] = GLIDER
        _assert_matches_dense(
            grid, GameConfig(gen_limit=50, convention=convention), tile=8)

    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_wrap_across_universe_edge(self, convention):
        """Live cells on the universe boundary: tile halos must wrap to
        the opposite side, including both corners."""
        grid = np.zeros((16, 24), np.uint8)
        grid[0, 0] = grid[0, 23] = grid[15, 0] = grid[15, 23] = 1
        grid[0, 1] = grid[1, 0] = grid[15, 22] = 1
        _assert_matches_dense(
            grid, GameConfig(gen_limit=20, convention=convention), tile=8)

    def test_similarity_disabled(self):
        g = np.zeros((16, 16), np.uint8)
        g[4:6, 4:6] = 1  # block would similar-exit; without the check it
        r = _assert_matches_dense(  # must run to the limit
            g, GameConfig(gen_limit=25, check_similarity=False), tile=8)
        assert r.exit_reason == "gen_limit"
        assert r.generations == 25

    def test_gen_limit_zero(self):
        g = np.zeros((16, 16), np.uint8)
        g[4:6, 4:6] = 1
        for convention in CONVENTIONS:
            _assert_matches_dense(
                g, GameConfig(gen_limit=0, convention=convention), tile=8)

    def test_dead_interior_tile_elided(self):
        """A dead tile with no live-ring neighbor is never simulated: the
        glider sits in one corner tile, so per-generation active tiles
        stay far below the 16-tile total."""
        grid = np.zeros((32, 32), np.uint8)
        grid[9:12, 9:12] = GLIDER  # interior of tile (1,1)
        board = SparseBoard.from_dense(grid, tile=8)
        result = simulate_sparse(board, GameConfig(gen_limit=4))
        # 4 generations of a glider touch at most a few tiles each step,
        # never all 16 — elision is doing its job.
        assert result.stats.tiles_active < 4 * 8
        assert result.stats.tiles_per_generation() < 8

    def test_activation_only_on_live_ring(self):
        """A live blob strictly interior to its tile (no ring cells) must
        not wake any neighbor."""
        grid = np.zeros((32, 32), np.uint8)
        grid[3:5, 3:5] = 1  # block, interior of tile (0,0)
        board = SparseBoard.from_dense(grid, tile=8)
        active = sparse_engine._active_set(board)
        assert active == {(0, 0)}

    def test_activation_corner(self):
        """A live cell ON a tile corner wakes all 8 neighbors (the
        diagonal neighbor sees it only through the corner halo cell)."""
        grid = np.zeros((32, 32), np.uint8)
        grid[15, 15] = 1  # bottom-right corner cell of tile (1, 1)
        board = SparseBoard.from_dense(grid, tile=8)
        active = sparse_engine._active_set(board)
        assert active == {(ty, tx) for ty in (0, 1, 2) for tx in (0, 1, 2)}

    def test_auto_engine_threshold(self):
        assert auto_engine(1 << 13, 1 << 13, 256) == "sparse"
        assert auto_engine(1 << 16, 1 << 16, 256) == "sparse"
        assert auto_engine(512, 512, 256) == "dense"
        # Indivisible extents stay dense even above the threshold.
        assert auto_engine((1 << 13) + 1, 1 << 13, 256) == "dense"


# ---------------------------------------------------------------------------
# Tile memo
# ---------------------------------------------------------------------------


class TestTileMemo:
    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_memo_hits_byte_identical(self, convention):
        """The central memo gate: a memo'd run's bytes — cells, count,
        exit — equal a memo-disabled run's, while the memo visibly
        absorbs kernel dispatches."""
        rng = np.random.default_rng(5)
        grid = (rng.random((24, 24)) < 0.35).astype(np.uint8)
        cfg = GameConfig(gen_limit=50, convention=convention)
        bare = simulate_sparse(SparseBoard.from_dense(grid, 8), cfg)
        memo = TileMemo(entries=4096)
        memod = simulate_sparse(SparseBoard.from_dense(grid, 8), cfg, memo)
        assert memod.generations == bare.generations
        assert memod.exit_reason == bare.exit_reason
        assert memod.board == bare.board
        # A second identical run is almost entirely memo hits.
        again = simulate_sparse(SparseBoard.from_dense(grid, 8), cfg, memo)
        assert again.board == bare.board
        assert again.stats.tiles_computed < bare.stats.tiles_computed
        assert again.stats.memo_hits > 0

    def test_repeated_pattern_stamps_hit(self):
        """Identical tile content ANYWHERE on the board shares memo
        entries: two far-apart glider stamps cost ~one stamp's kernels."""
        cfg = GameConfig(gen_limit=8)
        memo = TileMemo(entries=4096)
        board = SparseBoard(64, 64, 8)
        board.place(GLIDER, 9, 9)    # interior of tile (1,1)
        board.place(GLIDER, 41, 41)  # same intra-tile offset in (5,5)
        result = simulate_sparse(board, cfg, memo)
        assert result.stats.memo_hits > 0
        assert result.stats.tiles_computed < result.stats.tiles_active

    def test_memo_disk_tier_round_trip(self, tmp_path):
        block = np.zeros((10, 10), np.uint8)
        block[4, 4] = block[4, 5] = block[5, 4] = 1
        memo = TileMemo(entries=4, cas_dir=str(tmp_path))
        key = TileMemo.key(block, 8)
        from gol_tpu.sparse.memo import TileStep

        interior = np.ones((8, 8), np.uint8)
        memo.put(key, TileStep(interior=interior, alive=True, changed=False))
        # A fresh memo over the same directory serves from the CAS tier.
        memo2 = TileMemo(entries=4, cas_dir=str(tmp_path))
        hit = memo2.get(key)
        assert hit is not None
        assert hit.alive is True and hit.changed is False
        assert np.array_equal(hit.interior, interior)

    def test_memo_key_scoped_by_tile_size(self):
        block = np.zeros((10, 10), np.uint8)
        assert TileMemo.key(block, 8) != TileMemo.key(block, 16)


# ---------------------------------------------------------------------------
# Serve lane: sparse jobs through the scheduler + journal replay
# ---------------------------------------------------------------------------


def _sparse_job(**over):
    spec = dict(rle=GLIDER_RLE, place_x=5, place_y=9, tile=8, gen_limit=40)
    spec.update(over)
    return new_job(64, 64, None, **spec)


def _await(jobs, timeout=60):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if all(j.state == DONE for j in jobs):
            return
        time.sleep(0.01)
    raise AssertionError(
        f"jobs stuck: {[(j.id, j.state, j.error) for j in jobs]}"
    )


class TestSparseServe:
    def test_bucket_key_is_sparse(self):
        job = _sparse_job()
        key = batcher.bucket_for(job)
        assert key.kernel == batcher.SPARSE_KERNEL
        assert (key.height, key.width) == (64, 64)

    def test_scheduler_runs_sparse_job(self):
        sched = Scheduler(flush_age=0.01)
        sched.start()
        try:
            job = sched.submit(_sparse_job())
            _await([job])
        finally:
            sched.stop()
        ref_grid = np.zeros((64, 64), np.uint8)
        ref_grid[9:12, 5:8] = GLIDER
        ref = oracle.run(ref_grid, GameConfig(gen_limit=40))
        got = SparseBoard.from_rle(job.result.rle, 64, 64, 8)
        assert np.array_equal(got.to_dense(), ref.grid)
        assert job.result.generations == ref.generations
        assert job.result.grid is None
        assert job.result.population == 5
        assert job.result.tiles_simulated > 0

    def test_mixed_sparse_and_dense_buckets(self):
        rng = np.random.default_rng(2)
        dense_board = (rng.random((32, 32)) < 0.4).astype(np.uint8)
        sched = Scheduler(flush_age=0.01)
        sched.start()
        try:
            sparse = sched.submit(_sparse_job())
            dense = sched.submit(new_job(32, 32, dense_board, gen_limit=30))
            _await([sparse, dense])
        finally:
            sched.stop()
        ref = oracle.run(dense_board.copy(), GameConfig(gen_limit=30))
        assert np.array_equal(dense.result.grid, ref.grid)
        assert sparse.result.rle is not None

    def test_sparse_serving_metrics(self):
        sched = Scheduler(flush_age=0.01)
        sched.start()
        try:
            job = sched.submit(_sparse_job())
            _await([job])
        finally:
            sched.stop()
        counters = sched.metrics.snapshot()["counters"]
        gauges = sched.metrics.snapshot()["gauges"]
        assert counters["sparse_tiles_simulated_total"] > 0
        assert 0 < gauges["sparse_occupancy"] <= 1
        # Achieved work counts tiles x tile-area, not universe x gens.
        assert counters["serve_cell_updates_total"] == \
            job.result.cell_updates

    def test_sparse_job_not_result_cached(self):
        from gol_tpu.cache import ResultCache

        sched = Scheduler(flush_age=0.01, cache=ResultCache(memory_entries=8))
        sched.start()
        try:
            a = sched.submit(_sparse_job())
            _await([a])
            b = sched.submit(_sparse_job())
            _await([b])
        finally:
            sched.stop()
        assert a.fingerprint is None and b.fingerprint is None
        assert b.result.cached is None
        # Same answer both times regardless.
        assert a.result.rle == b.result.rle

    def test_occupancy_index_replay_via_journal(self, tmp_path):
        """The SIGKILL-shaped replay: a journaled-but-unfinished sparse
        job replays from its RLE spec (the occupancy index is rebuilt
        from the record — no dense cells anywhere in the journal) and
        re-runs to a byte-identical result."""
        journal = JobJournal(str(tmp_path))
        # "Crash" before any worker ran: submit into a scheduler that is
        # never started, so only the submit record lands.
        sched = Scheduler(journal=journal, flush_age=0.01)
        job = sched.submit(_sparse_job())
        journal.close()
        # Verify the journal record carries the spec, not cells.
        with open(journal.path, encoding="utf-8") as f:
            rec = json.loads(f.readline())
        assert rec["event"] == "submit"
        assert rec["job"]["rle"] == GLIDER_RLE
        assert "cells" not in rec["job"]
        # Restart: replay hands the job back; a fresh scheduler re-runs it.
        journal2 = JobJournal(str(tmp_path))
        replay = journal2.replay()
        assert [j.id for j in replay.pending] == [job.id]
        sched2 = Scheduler(journal=journal2, flush_age=0.01)
        sched2.resubmit_replayed(replay.pending)
        sched2.start()
        try:
            replayed = sched2.job(job.id)
            _await([replayed])
        finally:
            sched2.stop()
        # Identical to a direct sparse run of the same spec.
        direct = simulate_sparse(
            SparseBoard.from_pattern(GLIDER, 5, 9, 64, 64, 8),
            GameConfig(gen_limit=40),
        )
        assert replayed.result.rle == direct.board.to_rle()
        assert replayed.result.generations == direct.generations
        # And the done record replays as a sparse result on a THIRD boot.
        journal2.close()
        journal3 = JobJournal(str(tmp_path))
        replay3 = journal3.replay()
        journal3.close()
        assert replay3.pending == []
        restored = replay3.results[job.id]
        assert restored.grid is None
        assert restored.rle == replayed.result.rle
        assert restored.universe == (64, 64)

    def test_sparse_job_validation(self):
        with pytest.raises(ValueError, match="divide"):
            _sparse_job(tile=7)
        with pytest.raises(ValueError, match="does not fit"):
            _sparse_job(place_x=63)
        with pytest.raises(TypeError, match="string"):
            _sparse_job(rle=7)
        with pytest.raises(ValueError, match="either cells or rle"):
            Job(id="x", width=64, height=64,
                board=np.zeros((64, 64), np.uint8), rle=GLIDER_RLE)
        with pytest.raises(ValueError, match="B3/S23"):
            _sparse_job(rle="x = 3, y = 3, rule = B36/S23\n3o!")


# ---------------------------------------------------------------------------
# JobResult plumbing
# ---------------------------------------------------------------------------


class TestSparseJobResult:
    def test_done_record_round_trip(self, tmp_path):
        journal = JobJournal(str(tmp_path))
        job = _sparse_job()
        job.transition("scheduled")
        job.transition("running")
        job.result = JobResult(
            grid=None, generations=7, exit_reason="gen_limit",
            rle="x = 64, y = 64, rule = B3/S23\n!", population=0,
            universe=(64, 64),
        )
        job.transition(DONE)
        journal.record_done(job)
        journal.close()
        replay = JobJournal(str(tmp_path)).replay()
        got = replay.results[job.id]
        assert got.grid is None
        assert got.rle == job.result.rle
        assert got.population == 0
        assert got.universe == (64, 64)
        assert got.generations == 7
