"""ISSUE 7's job-granular observability tier: per-job timelines, the SLO
engine, the dispatch-gap sampler, flow events, and the ops surfaces.

The load-bearing assertions:

- a job's timeline **decomposes exactly**: the segment sum from ``accepted``
  to ``done`` equals its measured end-to-end latency, identically across
  the classic depth-1, pipelined, and resident-ring lanes, and its DONE
  milestone agrees with the journal (a done record exists iff the timeline
  completed);
- telemetry off stays the zero-allocation no-op path (``trace.flow`` while
  disabled records nothing);
- the SLO engine's multi-window burn rule: critical only when EVERY window
  burns, shedding only when explicitly enabled (observe-only is the pinned
  default), 429 + Retry-After on the admission path when it is;
- ``/metrics`` parity: the JSON variant carries the process-global
  registry's gauges/histograms under ``process`` while the Prometheus text
  contract stays serving-series-only.
"""

import importlib.util
import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from gol_tpu.config import GameConfig
from gol_tpu.io import text_grid
from gol_tpu.obs import (
    registry as obs_registry,
    report as obs_report,
    sampler as obs_sampler,
    slo as obs_slo,
    timeline as obs_timeline,
    top as obs_top,
    trace as obs_trace,
)
from gol_tpu.obs.registry import Registry, metric_label
from gol_tpu.serve import batcher
from gol_tpu.serve.jobs import DONE, JobJournal, new_job, priority_class
from gol_tpu.serve.scheduler import Scheduler
from gol_tpu.serve.server import GolServer


@pytest.fixture(autouse=True)
def _clean_trace():
    """Tracing off, ring empty and at its DEFAULT size around every test:
    obs trace state is process-global, and an earlier test file may have
    shrunk the ring (test_obs exercises bounded rings)."""
    obs_trace.enable(ring_size=obs_trace._DEFAULT_RING)
    obs_trace.disable()
    obs_trace.clear()
    yield
    obs_trace.enable(ring_size=obs_trace._DEFAULT_RING)
    obs_trace.disable()
    obs_trace.clear()


def _small_jobs(n=6, gen_limit=8, priority=None):
    jobs = []
    for i in range(n):
        side = 32 if i % 2 == 0 else 30  # two buckets: packed + masked
        kwargs = {} if priority is None else {"priority": priority}
        jobs.append(new_job(
            side, side, text_grid.generate(side, side, seed=100 + i),
            gen_limit=gen_limit, **kwargs,
        ))
    return jobs


def _run_scheduler(tmp_path, name, **sched_kwargs):
    journal = JobJournal(str(tmp_path / name))
    sched = Scheduler(journal=journal, flush_age=0.005, max_batch=4,
                      **sched_kwargs)
    jobs = _small_jobs()
    for job in jobs:
        sched.submit(job)
    sched.start()
    assert sched.drain(timeout=120)
    sched.stop(drain=False)
    replay = journal.replay()
    journal.close()
    return jobs, sched, replay


# ---------------------------------------------------------------------------
# Timelines
# ---------------------------------------------------------------------------


class TestTimeline:
    def test_segments_tile_the_timeline_exactly(self):
        tl = {"accepted": 1.0, "claimed": 1.5, "stage_start": 1.6,
              "staged": 1.9, "dispatched": 2.0, "readback_start": 2.2,
              "completed": 2.5, "done": 2.6, "journaled": 2.9}
        segs = obs_timeline.segments(tl)
        assert segs == {
            "queue_wait": 0.5, "batch_form": pytest.approx(0.1),
            "stage": pytest.approx(0.3), "dispatch": pytest.approx(0.1),
            "device": pytest.approx(0.2), "readback": pytest.approx(0.3),
            "finalize": pytest.approx(0.1), "journal": pytest.approx(0.3),
        }
        total = sum(v for k, v in segs.items() if k != "journal")
        assert total == pytest.approx(tl["done"] - tl["accepted"])
        out = obs_timeline.summary(tl)
        assert out["total_seconds"] == pytest.approx(1.6)
        assert out["journal_lag_seconds"] == pytest.approx(0.3)
        assert out["milestones"]["accepted"] == 0.0

    def test_partial_timeline_stays_wellformed(self):
        """A no-split lane (injected run_batch) has fewer milestones; the
        consecutive-present rule must still tile accepted -> done."""
        tl = {"accepted": 1.0, "claimed": 1.2, "done": 2.0}
        segs = obs_timeline.segments(tl)
        assert segs == {"queue_wait": pytest.approx(0.2),
                        "finalize": pytest.approx(0.8)}
        assert obs_timeline.summary({})["milestones"] == {}

    @pytest.mark.parametrize("lane,kwargs", [
        ("classic", dict(pipeline_depth=1)),
        ("pipelined", dict(pipeline_depth=2)),
        ("resident", dict(pipeline_depth=4, resident_ring=2)),
    ])
    def test_every_lane_yields_exact_timelines(self, tmp_path, lane, kwargs):
        """The ISSUE acceptance, per lane: every job's segment sum matches
        its end-to-end latency exactly, milestones are monotonic, and the
        DONE milestone agrees with the journal record."""
        jobs, _, replay = _run_scheduler(tmp_path, lane, **kwargs)
        for job in jobs:
            assert job.state == DONE
            tl = dict(job.timeline)
            # The full split runs in every real lane: all nine milestones.
            for m in obs_timeline.MILESTONES:
                assert m in tl, (lane, m)
            stamps = [tl[m] for m in obs_timeline.MILESTONES]
            assert stamps == sorted(stamps), (lane, tl)
            out = obs_timeline.summary(tl)
            seg_sum = sum(v for k, v in out["segments"].items()
                          if k != "journal")
            assert seg_sum == pytest.approx(out["total_seconds"], abs=1e-9)
            assert out["total_seconds"] == pytest.approx(
                job.finished_at - job.accepted_at, abs=1e-9
            )
            # DONE milestone <-> journal agreement, both directions.
            assert job.id in replay.results, (lane, job.id)
            assert tl["journaled"] >= tl["done"]
        assert not replay.pending

    def test_latency_and_cell_metrics_fed(self, tmp_path):
        jobs, sched, _ = _run_scheduler(tmp_path, "metrics")
        snap = sched.metrics.snapshot()
        hist = snap["histograms"]["job_latency_seconds"]
        assert hist["count"] == len(jobs)
        assert snap["histograms"]["job_latency_seconds_normal"]["count"] == len(jobs)
        cells = snap["counters"]["serve_cell_updates_total"]
        assert cells == sum(
            j.height * j.width * j.result.generations for j in jobs
        )
        bucket_counters = [
            k for k in snap["counters"]
            if k.startswith("serve_cell_updates_total_")
        ]
        assert len(bucket_counters) == 2  # the packed and masked buckets
        assert sum(snap["counters"][k] for k in bucket_counters) == cells

    def test_priority_class(self):
        assert priority_class(3) == "high"
        assert priority_class(0) == "normal"
        assert priority_class(-1) == "low"


# ---------------------------------------------------------------------------
# Flow events + chrome export + trace-report (satellite: resident exports)
# ---------------------------------------------------------------------------


class TestFlowEvents:
    def test_flow_disabled_records_nothing(self):
        obs_trace.disable()
        obs_trace.clear()
        obs_trace.flow("job", "abc", "s")
        assert obs_trace.snapshot() == []
        # The span no-op pin still holds alongside.
        assert obs_trace.span("x") is obs_trace._NOOP

    def test_bad_phase_rejected(self):
        obs_trace.enable()
        try:
            with pytest.raises(ValueError):
                obs_trace.tracer().flow("job", "abc", "x")
        finally:
            obs_trace.disable()
            obs_trace.clear()

    def test_resident_trace_roundtrips_with_flows(self, tmp_path):
        """Satellite 3: a traced resident-lane session exports
        serve.resident_loop spans plus job flow events; the Chrome JSON is
        well-formed Perfetto input and `gol trace-report` renders it."""
        obs_trace.enable()
        try:
            jobs, _, _ = _run_scheduler(
                tmp_path, "traced", pipeline_depth=4, resident_ring=2,
            )
            path = obs_trace.export_chrome(str(tmp_path / "trace.json"))
        finally:
            obs_trace.disable()
            obs_trace.clear()
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        events = doc["traceEvents"]
        assert isinstance(events, list) and events
        # Perfetto well-formedness: every event has name/ph/ts/pid/tid;
        # complete events carry dur; flow events carry id; timestamps are
        # sorted (the export contract).
        last_ts = None
        for e in events:
            for field in ("name", "ph", "ts", "pid", "tid"):
                assert field in e, e
            if e["ph"] == "X":
                assert "dur" in e
            else:
                assert e["ph"] in ("s", "t", "f")
                assert e.get("id")
            if last_ts is not None:
                assert e["ts"] >= last_ts
            last_ts = e["ts"]
        names = {e["name"] for e in events if e["ph"] == "X"}
        assert "serve.resident_loop" in names
        assert "serve.batch" in names
        # Every job's lifecycle flows: one start and one finish per id.
        starts = {e["id"] for e in events if e["ph"] == "s"}
        finishes = {e["id"] for e in events if e["ph"] == "f"}
        assert starts == finishes == {j.id for j in jobs}
        for e in events:
            if e["ph"] == "f":
                assert e["bp"] == "e"
        # And the report renders both artifacts without choking on flows.
        text = obs_report.render(path)
        assert "serve.resident_loop" in text
        assert "job flows:" in text
        assert f"{len(jobs)} started" in text

    def test_flight_dump_flows_counted_not_tabled(self, tmp_path):
        """Flow points ride the span ring; the report must count them
        instead of rendering 0-duration phases."""
        obs_trace.enable()
        try:
            with obs_trace.span("phase.a"):
                pass
            obs_trace.flow("job", "j1", "s")
            obs_trace.flow("job", "j1", "f")
            from gol_tpu.obs import recorder

            recorder.install(str(tmp_path))
            try:
                dump = recorder.trigger("test")
            finally:
                recorder.uninstall()
        finally:
            obs_trace.disable()
            obs_trace.clear()
        spans, meta = obs_report.load_spans(dump)
        assert [s["name"] for s in spans] == ["phase.a"]
        assert meta["flows"] == {"s": 1, "f": 1}
        text = obs_report.render(dump)
        assert "job flows: 1 started, 0 step(s), 1 finished" in text


# ---------------------------------------------------------------------------
# The SLO engine
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _engine(reg, clock, **kwargs):
    kwargs.setdefault("windows", (10.0, 60.0))
    return obs_slo.SloEngine(
        obs_slo.default_objectives(100, latency_target_s=1.0),
        registry=reg, clock=clock, **kwargs,
    )


class TestSloEngine:
    def test_error_rate_burn_over_windows(self):
        reg, clock = Registry(), _Clock()
        eng = _engine(reg, clock)
        eng.sample()
        reg.inc("jobs_accepted_total", 100)
        reg.inc("jobs_failed_total", 4)
        clock.advance(5)
        status = eng.evaluate()
        err = next(o for o in status["objectives"]
                   if o["name"] == "error_rate")
        # 4% failures against a 1% budget on both windows: burn 4, critical.
        assert err["windows"]["10s"]["observed"] == pytest.approx(0.04)
        assert err["windows"]["10s"]["burn"] == pytest.approx(4.0)
        assert err["status"] == obs_slo.CRITICAL
        assert status["status"] == obs_slo.CRITICAL

    def test_no_traffic_means_no_burn(self):
        reg, clock = Registry(), _Clock()
        eng = _engine(reg, clock)
        status = eng.evaluate()
        assert status["status"] == obs_slo.OK
        for o in status["objectives"]:
            assert o["burn"] == 0.0

    def test_latency_burn_and_recovery_rule(self):
        reg, clock = Registry(), _Clock()
        eng = _engine(reg, clock)
        eng.sample()  # baseline: count 0
        reg.observe("job_latency_seconds_normal", 3.0)  # 3x the 1s target
        clock.advance(5)
        status = eng.evaluate()
        lat = next(o for o in status["objectives"]
                   if o["name"] == "latency_p99_normal")
        assert lat["status"] == obs_slo.CRITICAL
        assert lat["burn"] == pytest.approx(3.0)
        # Once BOTH windows have an observation-free span, burn drops to 0
        # (the reservoir p99 alone cannot hold an alert up forever).
        clock.advance(100)
        eng.sample()
        clock.advance(100)
        status = eng.evaluate()
        lat = next(o for o in status["objectives"]
                   if o["name"] == "latency_p99_normal")
        assert lat["status"] == obs_slo.OK
        assert lat["burn"] == 0.0

    def test_multi_window_rule_needs_every_window(self):
        """A burst that only the short window sees must NOT alert: the
        binding burn is the minimum across windows."""
        reg, clock = Registry(), _Clock()
        eng = _engine(reg, clock)
        eng.sample()
        clock.advance(55)
        reg.inc("jobs_accepted_total", 10)
        eng.sample()
        clock.advance(5)
        # Fresh failures land inside the 10s window only; the 60s window
        # dilutes them over the earlier accepted traffic... with counters
        # both windows see the same totals here, so use saturation instead:
        reg.set_gauge("queue_depth", 95)  # 95% of capacity vs 80% target
        status = eng.evaluate()
        sat = next(o for o in status["objectives"]
                   if o["name"] == "queue_saturation")
        # Saturation max-over-window sees the spike in every window that
        # contains the newest sample -> burns everywhere (it is a gauge).
        assert sat["burn"] == pytest.approx(0.95 / 0.8, rel=1e-3)
        assert sat["status"] == obs_slo.WARNING  # 1.19 < critical 2.0

    def test_shed_only_when_enabled_and_critical(self):
        reg, clock = Registry(), _Clock()
        observe = _engine(reg, clock)
        reg.inc("jobs_accepted_total", 10)
        reg.inc("jobs_failed_total", 10)
        clock.advance(5)
        observe.evaluate()
        assert observe.should_shed() == (False, 0.0)

        shedding = _engine(reg, clock, shed=True, retry_after_s=7.0)
        shedding.sample()  # baseline BEFORE the new failures
        reg.inc("jobs_accepted_total", 10)
        reg.inc("jobs_failed_total", 10)
        clock.advance(5)
        shedding.evaluate()
        assert shedding.should_shed() == (True, 7.0)
        state = shedding.state()
        assert state["status"] == obs_slo.CRITICAL
        assert state["shed_active"] is True
        assert state["burn.error_rate"] > 0

    def test_render_status(self):
        reg, clock = Registry(), _Clock()
        eng = _engine(reg, clock)
        text = obs_slo.render_status(eng.evaluate())
        assert "SLO status: ok" in text
        assert "observe-only" in text
        assert "error_rate" in text
        # The flight-dump state form renders too.
        eng.evaluate()
        assert "burn" in obs_slo.render_status(eng.state())

    def test_render_flight_dump_state_reports_active_shedding(self):
        """The post-mortem's one operational fact — was the server rejecting
        traffic when it died — must survive the state record's flattened
        shed_enabled/shed_active keys."""
        reg, clock = Registry(), _Clock()
        eng = _engine(reg, clock, shed=True)
        eng.sample()
        reg.inc("jobs_accepted_total", 10)
        reg.inc("jobs_failed_total", 10)
        clock.advance(5)
        eng.evaluate()
        text = obs_slo.render_status(eng.state())
        assert "SLO status: critical" in text
        assert "shedding: enabled (ACTIVE)" in text
        assert "error_rate: burn" in text

    def test_objective_validation(self):
        with pytest.raises(ValueError):
            obs_slo.Objective(name="x", kind="nope", target=1, source="s")
        with pytest.raises(ValueError):
            obs_slo.Objective(name="x", kind="latency", target=0, source="s")
        with pytest.raises(ValueError):
            obs_slo.Objective(name="x", kind="error_rate", target=1, source="s")


# ---------------------------------------------------------------------------
# The dispatch-gap sampler
# ---------------------------------------------------------------------------


class TestSampler:
    def test_gap_gauges_from_counters_and_marginals(self):
        reg, clock = Registry(), _Clock()
        bucket = metric_label("256x256/c/packed")
        sampler = obs_sampler.ServeSampler(
            reg, interval=1.0, clock=clock,
            marginal_rates={bucket: 2000.0},
        )
        reg.inc("serve_cell_updates_total", 0)
        reg.inc(f"serve_cell_updates_total_{bucket}", 0)
        sampler.tick()  # first tick: baselines only, no gauges yet
        assert "dispatch_gap_ratio" not in reg.snapshot()["gauges"]
        reg.inc("serve_cell_updates_total", 1000)
        reg.inc(f"serve_cell_updates_total_{bucket}", 1000)
        clock.advance(1.0)
        sampler.tick()
        gauges = reg.snapshot()["gauges"]
        assert gauges[f"bucket_cell_updates_per_sec_{bucket}"] == pytest.approx(1000.0)
        # 1000 cells in 1s against a 2000/s roofline: gap ratio 0.5.
        assert gauges[f"dispatch_gap_ratio_{bucket}"] == pytest.approx(0.5)
        assert gauges["dispatch_gap_ratio"] == pytest.approx(0.5)
        assert gauges["serve_cell_updates_per_sec"] == pytest.approx(1000.0)
        # An idle tick keeps the last ratio (no decay to 0).
        clock.advance(1.0)
        sampler.tick()
        assert reg.snapshot()["gauges"]["dispatch_gap_ratio"] == pytest.approx(0.5)

    def test_unknown_bucket_work_suppresses_overall_ratio(self):
        """Work in a bucket with NO tuned marginal must not deflate the
        whole-service ratio (it would read as a standing false regression
        on a healthy service); per-bucket ratios still export."""
        reg, clock = Registry(), _Clock()
        sampler = obs_sampler.ServeSampler(
            reg, interval=1.0, clock=clock,
            marginal_rates={"known": 2000.0},
        )
        for name in ("serve_cell_updates_total",
                     "serve_cell_updates_total_known",
                     "serve_cell_updates_total_mystery"):
            reg.inc(name, 0)
        sampler.tick()
        reg.inc("serve_cell_updates_total_known", 1000)
        reg.inc("serve_cell_updates_total_mystery", 1000)
        reg.inc("serve_cell_updates_total", 2000)
        clock.advance(1.0)
        sampler.tick()
        gauges = reg.snapshot()["gauges"]
        assert gauges["dispatch_gap_ratio_known"] == pytest.approx(0.5)
        assert "dispatch_gap_ratio" not in gauges
        assert gauges["serve_cell_updates_per_sec"] == pytest.approx(2000.0)

    def test_without_marginals_rates_only(self):
        reg, clock = Registry(), _Clock()
        sampler = obs_sampler.ServeSampler(reg, interval=1.0, clock=clock)
        reg.inc("serve_cell_updates_total_b1", 0)
        sampler.tick()
        reg.inc("serve_cell_updates_total_b1", 500)
        clock.advance(2.0)
        sampler.tick()
        gauges = reg.snapshot()["gauges"]
        assert gauges["bucket_cell_updates_per_sec_b1"] == pytest.approx(250.0)
        assert "dispatch_gap_ratio_b1" not in gauges

    def test_thread_lifecycle(self):
        import threading

        reg = Registry()
        sampler = obs_sampler.ServeSampler(reg, interval=0.05)
        sampler.start()
        assert any(t.name == obs_sampler.THREAD_NAME
                   for t in threading.enumerate())
        sampler.stop()
        assert not any(t.name == obs_sampler.THREAD_NAME
                       for t in threading.enumerate())


# ---------------------------------------------------------------------------
# Tuned marginal rates (select <- tune handshake)
# ---------------------------------------------------------------------------


class TestMarginalRates:
    def test_select_reads_persisted_marginals(self, tmp_path, monkeypatch):
        from gol_tpu.tune import plans, select

        monkeypatch.setenv(plans.ENV_CACHE_PATH, str(tmp_path / "plans.json"))
        select.reset()
        try:
            assert select.marginal_rates() == {}
            store = plans.PlanStore()
            store.put(select.serve_fingerprint(), {
                "pad_quantum": 32,
                "batch_ladder": [1, 2, 4, 8, 16, 32, 64],
                "marginal": {"256x256_c_packed": 3.2e9,
                             "bogus": "not-a-rate", "zero": 0},
            })
            select.reset()
            assert select.marginal_rates() == {
                "256x256_c_packed": pytest.approx(3.2e9)
            }
        finally:
            select.reset()

    def test_measure_marginal_rate_spells_like_the_scheduler(self):
        """tune's marginal key must match the scheduler's per-bucket counter
        suffix — the sampler joins the two by string equality."""
        from gol_tpu.tune import measure
        from gol_tpu.tune.space import DEFAULT_SERVE_PLAN

        rates = measure.measure_marginal_rate(
            32, 32, "c", DEFAULT_SERVE_PLAN,
            gen_limit=2, batch=2, repeats=1,
        )
        job = new_job(32, 32, np.zeros((32, 32), np.uint8))
        want_key = metric_label(batcher.bucket_for(job).label())
        assert set(rates) == {want_key}
        assert rates[want_key] > 0


# ---------------------------------------------------------------------------
# Server surfaces: /slo, timeline endpoint, shedding, /metrics parity
# ---------------------------------------------------------------------------


def _http(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _wait(predicate, timeout=60):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def _submit_board(base, side=32, gen_limit=4, seed=5):
    board = text_grid.generate(side, side, seed=seed)
    return _http("POST", f"{base}/jobs", {
        "width": side, "height": side,
        "cells": text_grid.encode(board).decode("ascii"),
        "gen_limit": gen_limit,
    })


class TestServerSurfaces:
    @pytest.fixture
    def server(self, tmp_path):
        srv = GolServer(port=0, journal_dir=str(tmp_path / "journal"),
                        flush_age=0.01, sample_interval=0)
        srv.start()
        yield srv
        srv.shutdown()

    def _done_job(self, server):
        base = server.url
        status, raw, _ = _submit_board(base)
        assert status == 202
        jid = json.loads(raw)["id"]
        assert _wait(lambda: json.loads(
            _http("GET", f"{base}/jobs/{jid}")[1])["state"] == "done")
        return jid

    def test_timeline_endpoint(self, server):
        base = server.url
        jid = self._done_job(server)
        status, raw, _ = _http("GET", f"{base}/jobs/{jid}/timeline")
        assert status == 200
        tl = json.loads(raw)
        assert tl["state"] == "done"
        seg_sum = sum(v for k, v in tl["segments"].items() if k != "journal")
        assert seg_sum == pytest.approx(tl["total_seconds"], abs=1e-9)
        assert tl["journal_lag_seconds"] >= 0
        assert tl["milestones"]["accepted"] == 0.0
        assert _http("GET", f"{base}/jobs/nope/timeline")[0] == 404

    def test_slo_endpoint_and_observe_only_default(self, server):
        base = server.url
        # Early baseline sample (a real server's sampler thread does this).
        server.slo.sample()
        self._done_job(server)
        server.slo.evaluate()
        status, raw, _ = _http("GET", f"{base}/slo")
        assert status == 200
        slo = json.loads(raw)
        assert slo["status"] in ("ok", "warning", "critical")
        assert slo["shed"] == {"enabled": False, "active": False,
                               "retry_after_s": 5.0}
        assert {o["name"] for o in slo["objectives"]} == {
            "latency_p99_high", "latency_p99_normal", "latency_p99_low",
            "error_rate", "queue_saturation",
        }
        for o in slo["objectives"]:
            assert set(o["windows"]) == {"60s", "300s"}
        # Observe-only: even a critical engine state never sheds.
        assert server.should_shed() == (False, 0.0)

    def test_metrics_json_parity_and_prometheus_stability(self, server):
        base = server.url
        self._done_job(server)
        obs_registry.default().set_gauge("ring_slot_occupancy", 0.5)
        status, raw, _ = _http("GET", f"{base}/metrics?format=json")
        snap = json.loads(raw)
        # The serving snapshot, plus the process-global registry's gauges
        # and histogram summaries under "process" — what trace-report
        # renders from a flight dump, now live on /metrics.
        assert set(snap) >= {"counters", "gauges", "histograms", "process"}
        assert set(snap["process"]) == {"counters", "gauges", "histograms"}
        assert snap["process"]["gauges"]["ring_slot_occupancy"] == 0.5
        assert snap["process"]["counters"]["engine_batches_total"] >= 1
        assert "job_latency_seconds" in snap["histograms"]
        # Prometheus text: serving series only, and the PR-2 pinned lines
        # unchanged — no "process" leakage.
        status, raw, _ = _http("GET", f"{base}/metrics")
        text = raw.decode()
        assert "gol_serve_jobs_completed_total 1" in text
        assert 'gol_serve_run_latency_seconds{quantile="0.99"}' in text
        assert "process" not in text
        assert "engine_batches_total" not in text

    def test_shedding_server_429_with_retry_after(self, tmp_path):
        srv = GolServer(port=0, flush_age=0.01, sample_interval=0,
                        slo_shed=True, slo_latency_target=1e-9)
        srv.start()
        try:
            base = srv.url
            srv.slo.sample()  # the pre-traffic baseline
            status, raw, _ = _submit_board(base, seed=6)
            assert status == 202  # no latency samples yet: nothing burns
            jid = json.loads(raw)["id"]
            assert _wait(lambda: json.loads(
                _http("GET", f"{base}/jobs/{jid}")[1])["state"] == "done")
            srv.slo.evaluate()  # any completed job breaches a 1ns target
            status, raw, headers = _submit_board(base, seed=7)
            assert status == 429
            assert int(headers["Retry-After"]) >= 1
            assert "shedding" in json.loads(raw)["error"]
            assert srv.metrics.counter("jobs_shed_total") == 1
            # The flight-recorder state provider is registered while up.
            from gol_tpu.obs import recorder

            assert obs_slo.STATE_PROVIDER in recorder._state_providers
        finally:
            srv.shutdown()
        from gol_tpu.obs import recorder

        assert obs_slo.STATE_PROVIDER not in recorder._state_providers

    def test_sampler_thread_hygiene(self, tmp_path):
        import threading

        srv = GolServer(port=0, flush_age=0.01, sample_interval=0.05)
        srv.start()
        assert _wait(lambda: any(
            t.name == obs_sampler.THREAD_NAME for t in threading.enumerate()
        ), timeout=5)
        srv.shutdown()
        assert not any(t.name == obs_sampler.THREAD_NAME
                       for t in threading.enumerate())


# ---------------------------------------------------------------------------
# gol top rendering
# ---------------------------------------------------------------------------


class TestTop:
    def test_render_frame_sections(self):
        metrics = {
            "counters": {"jobs_accepted_total": 10, "jobs_completed_total": 9,
                         "jobs_failed_total": 1, "batches_total": 3},
            "gauges": {"queue_depth": 2, "inflight_batches": 1,
                       "dispatch_gap_ratio": 0.62,
                       "serve_cell_updates_per_sec": 1.5e9,
                       "bucket_cell_updates_per_sec_256x256_c_packed": 1.5e9,
                       "dispatch_gap_ratio_256x256_c_packed": 0.62},
            "histograms": {"job_latency_seconds": {
                "count": 9, "sum": 1.0, "p50": 0.1, "p95": 0.2, "p99": 0.3}},
            "process": {"gauges": {"ring_slot_occupancy": 0.75},
                        "histograms": {"dispatch_gap_seconds": {
                            "count": 4, "sum": 0.1, "p50": 0.01,
                            "p95": 0.02, "p99": 0.03}}},
        }
        slo = {"status": "warning", "windows_s": [60, 300],
               "objectives": [{"name": "error_rate", "status": "warning",
                               "windows": {"60s": {"burn": 1.2},
                                           "300s": {"burn": 1.1}}}]}
        frame = obs_top.render_frame(metrics, slo, ansi=False)
        assert "SLO WARNING" in frame
        assert "depth      2" in frame
        assert "ring occupancy" in frame
        assert "0.62 of tuned roofline" in frame
        assert "job_latency_seconds" in frame
        assert "error_rate" in frame and "1.200" in frame
        assert "256x256_c_packed" in frame
        # ANSI mode colors the status; plain mode must not.
        assert "\x1b[" not in frame
        assert "\x1b[33m" in obs_top.render_frame(metrics, slo, ansi=True)

    def test_render_frame_survives_unreachable_endpoints(self):
        frame = obs_top.render_frame({}, None, ansi=False)
        assert "/metrics unreachable" in frame
        assert "/slo unreachable" in frame


# ---------------------------------------------------------------------------
# bench_diff (satellite 1)
# ---------------------------------------------------------------------------


def _bench_diff():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "bench_diff.py")
    spec = importlib.util.spec_from_file_location("bench_diff", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchDiff:
    def test_within_tolerance_passes(self):
        bd = _bench_diff()
        doc = {"metric": "serve_rate", "value": 100.0, "unit": "boards/s",
               "detail": {"b1": 10.0}}
        new = {"metric": "serve_rate", "value": 95.0, "unit": "boards/s",
               "detail": {"b1": 10.5}}
        lines, regressed = bd.compare(doc, new, 0.10)
        assert not regressed
        assert "within tolerance" in lines[0]

    def test_higher_better_regression(self):
        bd = _bench_diff()
        old = {"metric": "serve_rate", "value": 100.0, "unit": "x"}
        new = {"metric": "serve_rate", "value": 80.0, "unit": "x"}
        lines, regressed = bd.compare(old, new, 0.10)
        assert regressed and "REGRESSION" in lines[0]
        # An improvement of the same size is NOT a regression.
        _, regressed = bd.compare(new, old, 0.10)
        assert not regressed

    def test_lower_better_direction(self):
        bd = _bench_diff()
        old = {"metric": "checkpoint_sync_seconds", "value": 1.0, "unit": "s"}
        slower = {"metric": "checkpoint_sync_seconds", "value": 1.5, "unit": "s"}
        _, regressed = bd.compare(old, slower, 0.10)
        assert regressed
        _, regressed = bd.compare(slower, old, 0.10)
        assert not regressed

    def test_mismatched_metrics_rejected(self):
        bd = _bench_diff()
        with pytest.raises(ValueError):
            bd.compare({"metric": "a", "value": 1}, {"metric": "b", "value": 1},
                       0.1)

    def test_nested_drift_reported_not_fatal(self):
        bd = _bench_diff()
        old = {"metric": "m", "value": 1.0, "unit": "x",
               "lanes": {"a": 1.0}, "env": {"jax": 4.0}}
        new = {"metric": "m", "value": 1.0, "unit": "x",
               "lanes": {"a": 2.0}, "env": {"jax": 5.0}}
        lines, regressed = bd.compare(old, new, 0.10)
        assert not regressed
        assert any("lanes.a" in line for line in lines)
        assert not any("env.jax" in line for line in lines)  # config-ignored

    def test_cli_exit_codes(self, tmp_path):
        bd = _bench_diff()
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps({"metric": "m", "value": 100, "unit": "x"}))
        b.write_text(json.dumps({"metric": "m", "value": 50, "unit": "x"}))
        assert bd.main([str(a), str(a)]) == 0
        assert bd.main([str(a), str(b)]) == 1
        assert bd.main([str(a), str(tmp_path / "missing.json")]) == 2


# ---------------------------------------------------------------------------
# gol submit's latency note (satellite 6)
# ---------------------------------------------------------------------------


class TestSubmitLatencyNote:
    def test_note_from_live_server(self, tmp_path):
        from gol_tpu import cli

        srv = GolServer(port=0, flush_age=0.01, sample_interval=0)
        srv.start()
        try:
            base = srv.url
            status, raw, _ = _submit_board(base, seed=9)
            jid = json.loads(raw)["id"]
            assert _wait(lambda: json.loads(
                _http("GET", f"{base}/jobs/{jid}")[1])["state"] == "done")
            note = cli._submit_latency_note(base, jid)
            assert "queue " in note and "total " in note and "ms" in note
            # Unknown job / dead server: the note degrades to nothing.
            assert cli._submit_latency_note(base, "nope") == ""
        finally:
            srv.shutdown()
        assert cli._submit_latency_note(base, jid) == ""
