"""VT100 renderer parity + bootstrap no-op + halo bench smoke."""

import io
import os
import subprocess
import sys

import numpy as np

from gol_tpu import render
from gol_tpu.parallel import bootstrap


def test_frame_matches_reference_codes():
    g = np.array([[1, 0], [0, 1]], np.uint8)
    f = render.frame(g)
    # Exact escape sequences of src/game.c:42-58: home, reverse-video double
    # space per live cell, plain double space per dead, next-line per row.
    assert f == (
        "\033[H"
        + "\033[07m  \033[m" + "  " + "\033[E"
        + "  " + "\033[07m  \033[m" + "\033[E"
    )


def test_animate_runs_and_stops_on_empty():
    g = np.zeros((8, 8), np.uint8)
    g[3, 3] = 1  # lone cell dies after one step
    out = io.StringIO()
    final = render.animate(g, 10, fps=0, out=out, sleep=lambda s: None)
    assert not final.any()
    assert out.getvalue().count("\033[H") == 2  # initial frame + one step


def test_bootstrap_noop_without_optin(monkeypatch):
    monkeypatch.delenv("GOL_MULTIHOST", raising=False)
    bootstrap.initialize()  # must not raise or try to form a cluster
    assert not bootstrap.is_multihost()


def test_bench_halo_smoke():
    env = {
        **os.environ,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "JAX_PLATFORMS": "cpu",
    }
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--halo", "--size", "64",
         "--mesh", "2x4", "--repeats", "1"],
        capture_output=True,
        text=True,
        env=env,
        cwd=repo,
    )
    assert r.returncode == 0, r.stderr
    import json

    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["metric"] == "halo_exchange_p50_latency"
    assert line["value"] > 0
