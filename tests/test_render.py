"""VT100 renderer parity + bootstrap no-op + halo bench smoke."""

import io
import json
import os
import subprocess
import sys

import numpy as np

from gol_tpu import render
from gol_tpu.parallel import bootstrap


def test_frame_matches_reference_codes():
    g = np.array([[1, 0], [0, 1]], np.uint8)
    f = render.frame(g)
    # Exact escape sequences of src/game.c:42-58: home, reverse-video double
    # space per live cell, plain double space per dead, next-line per row.
    assert f == (
        "\033[H"
        + "\033[07m  \033[m" + "  " + "\033[E"
        + "  " + "\033[07m  \033[m" + "\033[E"
    )


def test_animate_runs_and_stops_on_empty():
    g = np.zeros((8, 8), np.uint8)
    g[3, 3] = 1  # lone cell dies after one step
    out = io.StringIO()
    final = render.animate(g, 10, fps=0, out=out, sleep=lambda s: None)
    assert not final.any()
    assert out.getvalue().count("\033[H") == 2  # initial frame + one step


def test_bootstrap_noop_without_optin(monkeypatch):
    monkeypatch.delenv("GOL_MULTIHOST", raising=False)
    bootstrap.initialize()  # must not raise or try to form a cluster
    assert not bootstrap.is_multihost()


def _run_bench(*flags: str) -> dict:
    """Run bench.py on an 8-virtual-CPU host and parse its JSON line."""
    env = {
        **os.environ,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "JAX_PLATFORMS": "cpu",
    }
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), *flags],
        capture_output=True,
        text=True,
        env=env,
        cwd=repo,
    )
    assert r.returncode == 0, r.stderr
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_bench_halo_smoke():
    line = _run_bench("--halo", "--size", "64", "--mesh", "2x4", "--repeats", "1")
    assert line["metric"] == "halo_exchange_p50_latency"
    assert line["value"] > 0


def test_bench_packed_state_smoke():
    """The packed-state lane (bench.py --packed-state, implied by --config 5)
    runs the word-state engine end-to-end — here on an 8-virtual-CPU 2x4
    mesh, with a generation count past TEMPORAL_GENS so the deep-halo fused
    pass (not just the single-generation tail) is the path exercised."""
    from gol_tpu.ops import stencil_packed as sp

    line = _run_bench(
        "--packed-state", "--size", "128", "--mesh", "2x4",
        "--gen-limit", str(sp.TEMPORAL_GENS + 2), "--repeats", "1",
    )
    assert line["metric"] == "cell_updates_per_sec_per_chip"
    assert line["grid"] == "128x128" and line["chips"] == 8
    assert line["value"] > 0


def test_bench_workload_resolution():
    """resolve_workload's preset-then-default ordering: presets must fully
    pin their lane (the oracle config stays on the byte lane; config 5
    implies packed state), and the default workload only applies when
    neither --size nor --config was given."""
    import bench  # repo root is on sys.path via conftest

    def resolve(*argv, n_devices=1):
        args = bench.build_parser().parse_args(list(argv))
        bench.resolve_workload(args, n_devices=n_devices)
        return args

    a = resolve()
    assert (a.size, a.packed_state) == (65536, True)
    a = resolve("--config", "1")
    assert (a.size, a.packed_state, a.mesh) == (512, False, None)
    a = resolve("--config", "3", n_devices=1)
    assert (a.size, a.packed_state, a.mesh) == (8192, False, None)
    a = resolve("--config", "3", n_devices=4)
    assert (a.size, a.mesh) == (8192, "2x2")
    a = resolve("--config", "5", n_devices=16)
    assert (a.size, a.packed_state, a.mesh, a.gen_limit) == (
        65536, True, "4x4", 10000,
    )
    for flags in (
        ["--compare"], ["--halo"], ["--verify"],
        ["--kernel", "lax"], ["--kernel", "packed"],
    ):
        a = resolve(*flags)
        assert (a.size, a.packed_state) == (16384, False), flags
    a = resolve("--size", "4096")
    assert (a.size, a.packed_state) == (4096, False)


def test_bench_aot_compile_demotes(monkeypatch, capsys):
    """bench.py compiles through engine.compile_runner on a ladder runner
    (VERDICT r4 weak #4): a Mosaic-shaped compile failure in the packed
    kernel demotes down the ladder exactly as the CLI path does — the
    bench records the fallback kernel instead of crashing."""
    import bench
    from gol_tpu import engine
    from gol_tpu.ops import stencil_packed

    orig_step = stencil_packed.packed_step
    orig_multi = stencil_packed.packed_step_multi

    def step(cur, topo, *, force_jnp=False, force_interp=False):
        if not force_jnp:
            raise RuntimeError("simulated Mosaic compile OOM")
        return orig_step(cur, topo, force_jnp=True)

    def multi(cur, topo, *, force_jnp=False, force_interp=False):
        if not force_jnp:
            raise RuntimeError("simulated Mosaic compile OOM")
        return orig_multi(cur, topo, force_jnp=True)

    monkeypatch.setattr(stencil_packed, "packed_step", step)
    monkeypatch.setattr(stencil_packed, "packed_step_multi", multi)
    from gol_tpu import engine as _e

    _e.make_runner.cache_clear()
    try:
        rc = bench.main(["--size", "64", "--gen-limit", "5", "--repeats", "1"])
    finally:
        _e.make_runner.cache_clear()
    out = capsys.readouterr()
    assert rc == 0, out.err
    assert "falling back to 'packed-jnp'" in out.err
    line = json.loads(out.out.strip().splitlines()[-1])
    assert line["metric"] == "cell_updates_per_sec_per_chip"
    assert line["value"] > 0
