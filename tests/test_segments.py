"""Segmented execution: bit-exact with the whole-run loop, snapshot CLI flow.

The similarity counter and generation number carry across compiled segment
calls, so early exits fire on exactly the same generations as one while_loop
— including exits that land mid-segment or at a segment boundary.
"""

import os

import numpy as np
import pytest

from gol_tpu import cli, engine, oracle
from gol_tpu.config import Convention, GameConfig
from gol_tpu.io import text_grid


def _segmented_final(grid, config, segment, kernel="lax", mesh=None):
    last = None
    for gens, device_grid, stopped in engine.simulate_segments(
        grid, config, mesh, kernel, segment
    ):
        last = (gens, np.asarray(device_grid, dtype=np.uint8), stopped)
    return last


@pytest.mark.parametrize("segment", [1, 3, 7, 100])
@pytest.mark.parametrize("convention", [Convention.C, Convention.CUDA])
def test_segmented_matches_whole_run_random(segment, convention):
    rng = np.random.default_rng(13)
    g = rng.integers(0, 2, size=(24, 24), dtype=np.uint8)
    config = GameConfig(gen_limit=40, convention=convention)
    expect = oracle.run(g, config)
    gens, final, stopped = _segmented_final(g, config, segment)
    np.testing.assert_array_equal(final, expect.grid)
    assert gens == expect.generations
    assert stopped


@pytest.mark.parametrize("segment", [1, 2, 3, 4, 5])
@pytest.mark.parametrize("convention", [Convention.C, Convention.CUDA])
def test_segmented_early_exits_cross_boundaries(segment, convention):
    config = GameConfig(gen_limit=50, convention=convention)
    # Still life: similarity exit lands on generation 2-3 depending on
    # convention — exercised against every segment phase.
    g = np.zeros((16, 16), np.uint8)
    g[4:6, 4:6] = 1
    expect = oracle.run(g, config)
    gens, final, _ = _segmented_final(g, config, segment)
    np.testing.assert_array_equal(final, expect.grid)
    assert gens == expect.generations
    # Lone cell: empty exit on generation 1.
    g = np.zeros((16, 16), np.uint8)
    g[8, 8] = 1
    expect = oracle.run(g, config)
    gens, final, _ = _segmented_final(g, config, segment)
    np.testing.assert_array_equal(final, expect.grid)
    assert gens == expect.generations


@pytest.mark.parametrize("convention", [Convention.C, Convention.CUDA])
def test_segmented_packed_kernel(convention):
    """The blocked loops under resume scalars (nonzero gen0/counter0): the
    fused packed kernel takes _simulate_c_block / _simulate_cuda_block, so
    segment boundaries land mid-vote-block in both conventions."""
    rng = np.random.default_rng(17)
    g = rng.integers(0, 2, size=(32, 128), dtype=np.uint8)
    config = GameConfig(gen_limit=30, convention=convention)
    expect = oracle.run(g, config)
    gens, final, _ = _segmented_final(g, config, 7, kernel="packed")
    np.testing.assert_array_equal(final, expect.grid)
    assert gens == expect.generations


@pytest.mark.parametrize("segment", [1, 3, 5, 100])
def test_segmented_cuda_empty_exit_recovery(segment):
    """A mid-run CUDA empty exit (break-before-swap keeps the last non-empty
    generation) through the blocked loop's recovery replay, with the exit
    landing inside different resumed segments."""
    g = text_grid.generate(32, 32, seed=166, density=0.06)  # dies at gen 72
    config = GameConfig(gen_limit=200, convention=Convention.CUDA)
    expect = oracle.run(g, config)
    assert expect.grid.any()  # sanity: the kept state is the non-empty one
    gens, final, stopped = _segmented_final(g, config, segment, kernel="packed")
    np.testing.assert_array_equal(final, expect.grid)
    assert gens == expect.generations == 72
    assert stopped


def test_cli_snapshots(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rng = np.random.default_rng(23)
    g = rng.integers(0, 2, size=(16, 16), dtype=np.uint8)
    text_grid.write_grid("in.txt", g)
    snapdir = tmp_path / "snaps"
    rc = cli.main(
        [
            "16", "16", "in.txt",
            "--variant", "game",
            "--gen-limit", "10",
            "--snapshot-every", "4",
            "--snapshot-dir", str(snapdir),
        ]
    )
    assert rc == 0
    snaps = sorted(os.listdir(snapdir))
    assert snaps == ["gen_000004.out", "gen_000008.out", "gen_000010.out"]
    # Each snapshot is a valid, resumable input file holding that generation.
    expect = oracle.run(g, GameConfig(gen_limit=4))
    got = text_grid.read_grid(str(snapdir / "gen_000004.out"), 16, 16)
    np.testing.assert_array_equal(got, expect.grid)
    # And the final output file matches the whole run.
    expect10 = oracle.run(g, GameConfig(gen_limit=10))
    got10 = text_grid.read_grid("game_output.out", 16, 16)
    np.testing.assert_array_equal(got10, expect10.grid)


def test_packed_segments_match_whole_run():
    """Segmented packed state == one packed while_loop, bit-exact."""
    import jax.numpy as jnp

    from gol_tpu.ops import stencil_packed as sp

    rng = np.random.default_rng(31)
    g = rng.integers(0, 2, size=(32, 128), dtype=np.uint8)
    config = GameConfig(gen_limit=40)
    expect = oracle.run(g, config)
    words = sp.encode(jnp.asarray(g))
    last = None
    for gens, state, stopped in engine.simulate_packed_segments(
        words, g.shape, config, segment=7
    ):
        last = (gens, state, stopped)
    gens, state, stopped = last
    np.testing.assert_array_equal(np.asarray(sp.decode(state)), expect.grid)
    assert gens == expect.generations and stopped


def test_cli_packed_io_snapshots(tmp_path, monkeypatch):
    """--packed-io composes with --snapshot-every; snapshots round-trip
    through read_packed (the resume property on the packed lane)."""
    import jax

    from gol_tpu.io import packed_io
    from gol_tpu.ops import stencil_packed as sp

    monkeypatch.chdir(tmp_path)
    rng = np.random.default_rng(29)
    g = rng.integers(0, 2, size=(64, 64), dtype=np.uint8)
    text_grid.write_grid("in.txt", g)
    snapdir = tmp_path / "snaps"
    rc = cli.main(
        [
            "64", "64", "in.txt",
            "--variant", "collective",
            "--gen-limit", "10",
            "--packed-io",
            "--mesh", "2x2",
            "--snapshot-every", "4",
            "--snapshot-dir", str(snapdir),
        ]
    )
    assert rc == 0
    snaps = sorted(os.listdir(snapdir))
    assert snaps == ["gen_000004.out", "gen_000008.out", "gen_000010.out"]
    # Snapshot files are plain text (byte-compatible with every variant)...
    expect4 = oracle.run(g, GameConfig(gen_limit=4))
    got4 = text_grid.read_grid(str(snapdir / "gen_000004.out"), 64, 64)
    np.testing.assert_array_equal(got4, expect4.grid)
    # ...and resumable through the packed reader itself.
    words4 = packed_io.read_packed(str(snapdir / "gen_000004.out"), 64, 64)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(sp.decode(words4))), expect4.grid
    )
    # Final output equals the whole (unsegmented) packed run.
    expect10 = oracle.run(g, GameConfig(gen_limit=10))
    got10 = text_grid.read_grid("collective_output.out", 64, 64)
    np.testing.assert_array_equal(got10, expect10.grid)


@pytest.mark.parametrize("convention", [Convention.C, Convention.CUDA])
@pytest.mark.parametrize(
    "mesh_a,mesh_b", [((2, 2), (2, 4)), ((2, 4), None), (None, (4, 2))]
)
def test_resume_across_topologies(convention, mesh_a, mesh_b):
    """Elastic reconfiguration: a mid-run segment state moves between meshes
    (or to/from a single device) and the continued run stays bit-exact with
    one uninterrupted loop — generation counter AND similarity phase carry.
    The reference cannot do this at all: its only resume path is the final
    output file, with the similarity phase lost (src/game.c:25-40,154-165).
    """
    import jax

    from gol_tpu.parallel import make_mesh

    rng = np.random.default_rng(77)
    g = rng.integers(0, 2, size=(32, 64), dtype=np.uint8)
    config = GameConfig(gen_limit=40, convention=convention)
    expect = oracle.run(g, config)

    def runner_for(mesh_shape):
        mesh = make_mesh(*mesh_shape) if mesh_shape else None
        return engine.make_segment_runner((32, 64), config, mesh, "lax"), mesh

    # Phase 1: 13 generations (an awkward offset for the freq-3 counter) on A.
    import jax.numpy as jnp

    run_a, mesh_a_obj = runner_for(mesh_a)
    gen0 = engine._GEN_START[config.convention]
    seg_end = gen0 + 13 - (1 if config.convention == Convention.C else 0)
    state_a = engine.put_grid(g, mesh_a_obj)
    state, gen, counter, stopped = run_a(
        state_a, jnp.int32(gen0), jnp.int32(0), jnp.int32(seg_end)
    )
    assert not bool(stopped)
    # The "checkpoint": host bytes + the two loop scalars (a real checkpoint
    # serializes all three; device arrays committed to mesh A must not leak
    # their sharding into mesh B's compiled call).
    host_state = np.asarray(jax.device_get(state), dtype=np.uint8)
    gen_ck, counter_ck = int(gen), int(counter)

    # Phase 2: rehydrate on B and run to completion.
    run_b, mesh_b_obj = runner_for(mesh_b)
    state_b = engine.put_grid(host_state, mesh_b_obj)
    state, gen, counter, stopped = run_b(
        state_b, jnp.int32(gen_ck), jnp.int32(counter_ck),
        jnp.int32(config.gen_limit),
    )
    assert bool(stopped)
    final = np.asarray(jax.device_get(state), dtype=np.uint8)
    np.testing.assert_array_equal(final, expect.grid)
    assert engine._REPORT[config.convention](int(gen)) == expect.generations


@pytest.mark.parametrize("convention", [Convention.C, Convention.CUDA])
@pytest.mark.parametrize("freq,split", [(3, 13), (3, 12), (1, 7), (4, 10)])
def test_resume_scalars_realign_similarity_phase(convention, freq, split):
    """engine.resume_scalars: a snapshot after N generations plus N alone
    reconstructs the loop scalars — the continued run is bit-exact with the
    uninterrupted one, early exits included, at every counter phase."""
    rng = np.random.default_rng(91)
    g = rng.integers(0, 2, size=(24, 32), dtype=np.uint8)
    config = GameConfig(gen_limit=40, similarity_frequency=freq,
                        convention=convention)
    expect = oracle.run(g, config)
    assert expect.generations > split  # split lands mid-run, not post-exit

    # The snapshot: the state after `split` generations (no early exit yet).
    first = GameConfig(gen_limit=split, similarity_frequency=freq,
                       convention=convention)
    snap = engine.simulate(g, first, kernel="lax").grid

    last = None
    for last in engine.simulate_segments(
        snap, config, None, "lax", segment=5, completed=split
    ):
        pass
    gens, final, stopped = last
    np.testing.assert_array_equal(
        np.asarray(final, dtype=np.uint8), expect.grid
    )
    assert gens == expect.generations and stopped


def test_cli_resume_gen_matches_uninterrupted(tmp_path, monkeypatch):
    """CLI crash-recovery flow: snapshot at gen 6, resume with --resume-gen 6,
    final output bytes and printed Generations match the uninterrupted run."""
    monkeypatch.chdir(tmp_path)
    rng = np.random.default_rng(19)
    g = rng.integers(0, 2, size=(32, 32), dtype=np.uint8)
    text_grid.write_grid("input.txt", g)

    def run(*argv):
        r = cli.main(["run", "32", "32", *argv])
        assert r == 0

    run("input.txt", "--variant", "game", "--gen-limit", "20",
        "--output", "whole.out")
    run("input.txt", "--variant", "game", "--gen-limit", "20",
        "--snapshot-every", "6", "--snapshot-dir", "snaps",
        "--output", "ignored.out")
    # "Crash" after the first snapshot: resume from gen_000006.out.
    run("snaps/gen_000006.out", "--variant", "game", "--gen-limit", "20",
        "--resume-gen", "6", "--output", "resumed.out")
    whole = open("whole.out", "rb").read()
    resumed = open("resumed.out", "rb").read()
    assert whole == resumed
    # And composing --resume-gen with further snapshots keeps absolute names.
    run("snaps/gen_000006.out", "--variant", "game", "--gen-limit", "20",
        "--resume-gen", "6", "--snapshot-every", "7",
        "--snapshot-dir", "snaps2", "--output", "resumed2.out")
    assert open("resumed2.out", "rb").read() == whole
    names = sorted(os.listdir("snaps2"))
    assert names and names[0] == "gen_000013.out"


def test_cli_resume_gen_validation(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    text_grid.write_grid("in.txt", np.ones((8, 8), np.uint8))
    rc = cli.main(["run", "8", "8", "in.txt", "--gen-limit", "10",
                   "--resume-gen", "25"])
    assert rc == 1
    assert "exceeds --gen-limit" in capsys.readouterr().err
    rc = cli.main(["run", "8", "8", "in.txt", "--resume-gen", "-1"])
    assert rc == 1
    rc = cli.main(["run", "8", "8", "in.txt", "--host", "--resume-gen", "3"])
    assert rc == 1
