"""The packed binary wire format (io/wire.py) end to end.

Three load-bearing contracts, each pinned here:

- **codec soundness**: random boards round-trip at any width (multiples of
  32 and not), the words lane encodes byte-identically to the grid lane,
  and truncated/CRC-corrupted/alien frames are rejected loudly — a frame
  parses whole or not at all.
- **format equivalence**: the same board submitted as text and as a packed
  frame produces bit-identical results through a REAL server and a REAL
  router, fetched through either result encoding; the text path stays
  byte-identical to pre-wire behavior (same response keys, same grid
  string, same routing call shape).
- **graceful degradation**: new clients against old servers (415/400 →
  retry as text, once, logged) and old clients against new servers (the
  JSON path untouched) both complete correctly.
"""

import json
import socket
import struct
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from gol_tpu import engine, oracle
from gol_tpu.config import Convention, GameConfig
from gol_tpu.io import bitpack, text_grid, wire
from gol_tpu.serve import batcher
from gol_tpu.serve.jobs import new_job
from gol_tpu.serve.server import GolServer, _decode_cells
from gol_tpu.obs import registry as obs_registry

CONVENTIONS = [Convention.C, Convention.CUDA]


def _http(method, url, data=None, headers=None, timeout=30):
    """(status, response content type, body bytes) over stdlib urllib."""
    req = urllib.request.Request(url, data=data, headers=headers or {},
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.headers.get("Content-Type", ""), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type", ""), e.read()


def _submit_text(base, board, **fields):
    body = {"width": board.shape[1], "height": board.shape[0],
            "cells": text_grid.encode(board).decode("ascii"), **fields}
    status, _, raw = _http("POST", f"{base}/jobs", json.dumps(body).encode(),
                           {"Content-Type": "application/json"})
    return status, json.loads(raw)


def _submit_packed(base, board, **fields):
    status, _, raw = _http("POST", f"{base}/jobs",
                           wire.encode_frame(fields, grid=board),
                           {"Content-Type": wire.CONTENT_TYPE})
    return status, json.loads(raw)


def _wait_done(base, job_id, timeout=60):
    import time

    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        _, _, raw = _http("GET", f"{base}/jobs/{job_id}")
        if json.loads(raw).get("state") == "done":
            return True
        time.sleep(0.01)
    return False


def _result_text(base, job_id):
    status, _, raw = _http("GET", f"{base}/result/{job_id}")
    assert status == 200, raw
    payload = json.loads(raw)
    return payload, text_grid.decode(
        payload["grid"].encode("ascii"), payload["width"], payload["height"]
    )


def _result_packed(base, job_id):
    status, ctype, raw = _http("GET", f"{base}/result/{job_id}",
                               headers={"Accept": wire.CONTENT_TYPE})
    assert status == 200, raw
    assert wire.is_packed(ctype), ctype
    frame = wire.decode_frame(raw)
    return frame.meta, frame.grid()


class TestWireCodec:
    @pytest.mark.parametrize("shape", [
        (1, 1), (5, 37), (32, 32), (40, 31), (3, 97), (64, 64), (17, 160),
    ])
    def test_round_trip_random_boards(self, shape):
        h, w = shape
        grid = (np.random.default_rng(h * 1000 + w).random((h, w)) < 0.5
                ).astype(np.uint8)
        meta = {"gen_limit": 7, "convention": "cuda"}
        frame = wire.encode_frame(meta, grid=grid)
        decoded = wire.decode_frame(frame)
        assert decoded.meta == meta
        assert (decoded.width, decoded.height) == (w, h)
        np.testing.assert_array_equal(decoded.grid(), grid)

    def test_words_lane_byte_identical_to_grid_lane(self):
        grid = text_grid.generate(40, 24, seed=3)  # width not % 32
        f1 = wire.encode_frame({"a": 1}, grid=grid)
        d = wire.decode_frame(f1)
        f2 = wire.encode_frame({"a": 1}, words=d.words, width=40, height=24)
        assert f1 == f2

    def test_packing_convention_is_bitpack(self):
        """Bit j of word w = column 32w+j — the wire payload IS the
        engine's staging layout, pinned against io/bitpack.py itself."""
        grid = text_grid.generate(64, 4, seed=9)
        frame = wire.decode_frame(wire.encode_frame({}, grid=grid))
        np.testing.assert_array_equal(frame.words, bitpack.pack_words(grid))

    @pytest.mark.parametrize("shape", [(0, 16), (16, 0), (0, 0)])
    def test_zero_area_edges(self, shape):
        h, w = shape
        grid = np.zeros((h, w), np.uint8)
        decoded = wire.decode_frame(wire.encode_frame({}, grid=grid))
        assert decoded.grid().shape == (h, w)

    def test_truncated_frames_rejected(self):
        frame = wire.encode_frame({"k": 1}, grid=text_grid.generate(8, 64, seed=1))
        for cut in (0, 3, wire.HEADER_SIZE - 1, wire.HEADER_SIZE + 2,
                    len(frame) - 1):
            with pytest.raises(wire.WireError):
                wire.decode_frame(frame[:cut])

    def test_trailing_garbage_rejected(self):
        frame = wire.encode_frame({}, grid=text_grid.generate(8, 64, seed=1))
        with pytest.raises(wire.WireError, match="trailing garbage|truncated"):
            wire.decode_frame(frame + b"\x00")

    def test_crc_corruption_rejected(self):
        frame = bytearray(
            wire.encode_frame({}, grid=text_grid.generate(8, 64, seed=2))
        )
        frame[-1] ^= 0x40
        with pytest.raises(wire.WireError, match="CRC"):
            wire.decode_frame(bytes(frame))

    def test_bad_magic_rejected(self):
        frame = bytearray(wire.encode_frame({}, grid=np.ones((1, 32), np.uint8)))
        frame[:4] = b"NOPE"
        with pytest.raises(wire.WireError, match="magic"):
            wire.decode_frame(bytes(frame))

    def test_newer_version_is_unsupported_not_malformed(self):
        frame = bytearray(wire.encode_frame({}, grid=np.ones((1, 32), np.uint8)))
        struct.pack_into("<H", frame, 4, wire.VERSION + 1)
        with pytest.raises(wire.UnsupportedWire):
            wire.decode_frame(bytes(frame))
        with pytest.raises(wire.UnsupportedWire):
            wire.peek(bytes(frame))

    def test_meta_must_be_object(self):
        grid = np.ones((1, 32), np.uint8)
        frame = wire.encode_frame({}, grid=grid)
        words = wire.decode_frame(frame).words
        # Hand-build a frame whose meta is a JSON array.
        meta_blob = b"[1,2]"
        payload = words.tobytes()
        import zlib

        header = struct.pack("<4sHHIIII", wire.MAGIC, wire.VERSION, 0,
                             32, 1, len(meta_blob), zlib.crc32(payload))
        with pytest.raises(wire.WireError, match="JSON object"):
            wire.decode_frame(header + meta_blob + payload)

    def test_peek_reads_header_and_meta_only(self):
        grid = text_grid.generate(96, 16, seed=4)  # (16, 96) board
        frame = wire.encode_frame({"gen_limit": 5}, grid=grid)
        # Chop the payload off entirely: peek must still answer (the
        # router places from the header; only decode_frame validates the
        # payload).
        w, h, meta = wire.peek(frame[:wire.HEADER_SIZE + len(b'{"gen_limit":5}')])
        assert (w, h, meta) == (96, 16, {"gen_limit": 5})

    def test_payload_crc_helper_matches_header(self):
        frame = wire.encode_frame({}, grid=text_grid.generate(8, 32, seed=5))
        import zlib

        words = wire.decode_frame(frame).words
        assert wire.payload_crc(frame) == zlib.crc32(words.tobytes())


class TestBodyCaps:
    def test_caps_by_content_type(self):
        assert wire.max_body_bytes(None) == wire.MAX_BODY_TEXT
        assert wire.max_body_bytes("application/json") == wire.MAX_BODY_TEXT
        assert wire.max_body_bytes("text/plain") == wire.MAX_BODY_TEXT
        assert wire.max_body_bytes(wire.CONTENT_TYPE) == wire.MAX_BODY_PACKED
        assert wire.max_body_bytes(
            wire.CONTENT_TYPE + "; charset=binary"
        ) == wire.MAX_BODY_PACKED
        assert wire.MAX_BODY_PACKED < wire.MAX_BODY_TEXT

    def test_same_board_universe_both_formats(self):
        """The boundary pin: for EVERY square side through the cutover
        window, the text and packed caps give the SAME accept/reject
        verdict — the caps bound one AREA universe, not one byte count
        (both flip exactly at 8192^2). Every side is checked, not a
        stride: an off-by-a-few-rows window where one format accepts
        what the other rejects is precisely the regression this pins."""

        def text_bytes(side):
            # JSON body: cells string is side*(side+1) chars, plus field
            # framing (~100 bytes).
            return side * (side + 1) + 100

        def packed_bytes(side):
            return (wire.HEADER_SIZE + 100
                    + side * wire.words_per_row(side) * 4)

        flips = set()
        for side in range(8000, 8400):
            text_ok = text_bytes(side) <= wire.MAX_BODY_TEXT
            packed_ok = packed_bytes(side) <= wire.MAX_BODY_PACKED
            assert text_ok == packed_ok, (side, text_ok, packed_ok)
            if not text_ok:
                flips.add(side)
        assert min(flips) == 8192  # the shared cutover side

    def test_http_cap_reads_content_type(self, tmp_path):
        """A Content-Length over the packed cap but under the text cap is
        rejected for a packed body and (at the cap-check layer) admitted
        for a JSON one — enforced before any body byte is read."""
        srv = GolServer(port=0, flush_age=0.01)
        srv.start()
        try:
            host, port = srv.address
            length = wire.MAX_BODY_PACKED + 1

            def head_only(ctype):
                s = socket.create_connection((host, port), timeout=10)
                try:
                    s.sendall(
                        f"POST /jobs HTTP/1.1\r\nHost: {host}\r\n"
                        f"Content-Type: {ctype}\r\n"
                        f"Content-Length: {length}\r\n\r\n".encode()
                    )
                    # The cap check fires on the header alone; the JSON
                    # lane instead starts reading the (absent) body and
                    # times out client-side — shutdown to force its answer.
                    s.settimeout(5)
                    return s.recv(200).decode(errors="replace")
                finally:
                    s.close()

            reply = head_only(wire.CONTENT_TYPE)
            assert " 400 " in reply.splitlines()[0]
        finally:
            srv.shutdown()


class TestServerWire:
    @pytest.fixture
    def server(self):
        srv = GolServer(port=0, flush_age=0.01)
        srv.start()
        yield srv
        srv.shutdown()

    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_packed_submit_matches_text_and_oracle(self, server, convention):
        base = server.url
        board = text_grid.generate(32, 32, seed=21)
        st, p_text = _submit_text(base, board, gen_limit=12,
                                  convention=convention)
        assert st == 202
        st, p_packed = _submit_packed(base, board, gen_limit=12,
                                      convention=convention)
        assert st == 202
        assert set(p_text) == set(p_packed) == {"id", "state"}
        for jid in (p_text["id"], p_packed["id"]):
            assert _wait_done(base, jid)
        want = oracle.run(board, GameConfig(gen_limit=12,
                                            convention=convention))
        # All four (submit format x result format) combinations agree.
        for jid in (p_text["id"], p_packed["id"]):
            payload, grid_t = _result_text(base, jid)
            meta, grid_p = _result_packed(base, jid)
            np.testing.assert_array_equal(grid_t, want.grid)
            np.testing.assert_array_equal(grid_p, want.grid)
            assert payload["generations"] == want.generations
            assert meta["generations"] == want.generations
            assert meta["exit_reason"] == payload["exit_reason"]
            assert meta["id"] == jid

    def test_text_result_payload_shape_pinned(self, server):
        """Old-client compat: the JSON result payload's keys and grid
        string are exactly the pre-wire contract."""
        base = server.url
        board = text_grid.generate(30, 30, seed=23)  # masked bucket too
        st, p = _submit_text(base, board, gen_limit=4)
        assert st == 202
        assert _wait_done(base, p["id"])
        payload, grid = _result_text(base, p["id"])
        assert set(payload) == {"id", "generations", "exit_reason",
                                "width", "height", "grid"}
        assert payload["grid"] == text_grid.encode(grid).decode("ascii")

    def test_packed_submit_nonpacked_width(self, server):
        """Widths that don't pack ride the same frame (padded final word);
        the job stages through the masked bucket like its text twin."""
        base = server.url
        board = text_grid.generate(30, 30, seed=25)
        st, p = _submit_packed(base, board, gen_limit=6)
        assert st == 202
        assert _wait_done(base, p["id"])
        want = oracle.run(board, GameConfig(gen_limit=6))
        _, grid = _result_packed(base, p["id"])
        np.testing.assert_array_equal(grid, want.grid)

    def test_unknown_wire_family_member_is_415(self, server):
        st, _, raw = _http("POST", f"{server.url}/jobs", b"xx",
                           {"Content-Type": "application/x-gol-packed-v9"})
        assert st == 415, raw
        assert "error" in json.loads(raw)

    def test_newer_frame_version_is_415(self, server):
        frame = bytearray(
            wire.encode_frame({"gen_limit": 1},
                              grid=np.zeros((32, 32), np.uint8))
        )
        struct.pack_into("<H", frame, 4, wire.VERSION + 1)
        st, _, raw = _http("POST", f"{server.url}/jobs", bytes(frame),
                           {"Content-Type": wire.CONTENT_TYPE})
        assert st == 415, raw

    def test_malformed_packed_bodies_are_400(self, server):
        base = server.url
        good = wire.encode_frame({"gen_limit": 1},
                                 grid=text_grid.generate(32, 32, seed=1))
        corrupt = bytearray(good)
        corrupt[-2] ^= 0xFF
        for body in (b"", b"junk" * 8, good[:-4], bytes(corrupt)):
            st, _, raw = _http("POST", f"{base}/jobs", body,
                               {"Content-Type": wire.CONTENT_TYPE})
            assert st == 400, (body[:16], st, raw)
            assert "error" in json.loads(raw)

    def test_packed_meta_must_not_smuggle_geometry(self, server):
        board = np.zeros((32, 32), np.uint8)
        for key in ("cells", "width", "height", "words"):
            frame = wire.encode_frame({key: 1}, grid=board)
            st, _, raw = _http("POST", f"{server.url}/jobs", frame,
                               {"Content-Type": wire.CONTENT_TYPE})
            assert st == 400, (key, raw)

    def test_packed_field_validation_matches_text(self, server):
        """Wrong-typed fields in frame meta 400 exactly like JSON bodies
        (same Job validation underneath)."""
        board = np.zeros((32, 32), np.uint8)
        for bad in ({"priority": None}, {"gen_limit": "x"},
                    {"check_similarity": "false"}, {"no_cache": "yes"}):
            st, p = _submit_packed(server.url, board, **bad)
            assert st == 400, (bad, p)
            st, p = _submit_text(server.url, board, **bad)
            assert st == 400, (bad, p)


class TestErrorContract:
    """Satellite: every malformed-board shape answers 400 with the JSON
    error contract — never a 500, never a silently-cropped board."""

    @pytest.fixture
    def server(self):
        srv = GolServer(port=0, flush_age=0.01)
        srv.start()
        yield srv
        srv.shutdown()

    def _submit_cells(self, base, cells, width=32, height=32):
        body = {"width": width, "height": height, "cells": cells,
                "gen_limit": 1}
        return _http("POST", f"{base}/jobs",
                     json.dumps(body).encode(),
                     {"Content-Type": "application/json"})

    def test_short_cells_400(self, server):
        st, _, raw = self._submit_cells(server.url, "1" * 10)
        assert st == 400
        assert "cells" in json.loads(raw)["error"]

    def test_long_cells_400_not_truncated(self, server):
        """The pre-wire server silently truncated extra cells; now the
        length must match the declared geometry exactly."""
        st, _, raw = self._submit_cells(server.url, "1" * (32 * 33 + 7))
        assert st == 400, raw
        assert "exactly" in json.loads(raw)["error"]

    def test_non_ascii_cells_400(self, server):
        for cells in ["é" * (32 * 33), "01☃" + "0" * (32 * 33 - 3)]:
            st, _, raw = self._submit_cells(server.url, cells)
            assert st == 400, raw
            assert "ASCII" in json.loads(raw)["error"]

    def test_non_string_cells_400(self, server):
        for cells in [123, None, ["0", "1"], {"a": 1}]:
            st, _, raw = self._submit_cells(server.url, cells)
            assert st == 400, (cells, raw)

    def test_wellformed_variants_still_accepted(self, server):
        """The strictness must not reject LEGAL bodies: with and without
        newline columns."""
        board = text_grid.generate(32, 32, seed=2)
        with_newlines = text_grid.encode(board).decode("ascii")
        flat = with_newlines.replace("\n", "")
        for cells in (with_newlines, flat):
            st, _, raw = self._submit_cells(server.url, cells)
            assert st == 202, raw

    def test_decode_cells_unit(self):
        board = text_grid.generate(8, 8, seed=3)
        cells = text_grid.encode(board).decode("ascii")
        np.testing.assert_array_equal(_decode_cells(cells, 8, 8), board)
        with pytest.raises(ValueError):
            _decode_cells(cells + "1", 8, 8)
        with pytest.raises(TypeError):
            _decode_cells(b"0" * 64, 8, 8)  # bytes is not str


class TestPackedStaging:
    def test_packed_submit_retains_words(self):
        srv = GolServer(port=0, flush_age=10.0)
        try:
            board = text_grid.generate(32, 32, seed=31)
            out = srv.submit_packed(wire.encode_frame({"gen_limit": 1},
                                                      grid=board))
            job = srv.scheduler.job(out["id"])
            assert job.words is not None
            np.testing.assert_array_equal(job.words,
                                          bitpack.pack_words(board))
            # Unpackable width: board decodes, words drop.
            board2 = text_grid.generate(30, 30, seed=32)
            out2 = srv.submit_packed(wire.encode_frame({"gen_limit": 1},
                                                       grid=board2))
            assert srv.scheduler.job(out2["id"]).words is None
        finally:
            srv.httpd.server_close()

    def test_all_words_batch_skips_packbits(self):
        """engine_stage_packs_total must NOT move when every job of a
        packed bucket carries wire words — and the staged operand must be
        byte-identical to the classic stack-and-pack path."""
        boards = [text_grid.generate(32, 32, seed=40 + i) for i in range(3)]
        jobs_words = [
            new_job(32, 32, b, gen_limit=5, words=bitpack.pack_words(b))
            for b in boards
        ]
        jobs_plain = [new_job(32, 32, b, gen_limit=5) for b in boards]
        key = batcher.bucket_for(jobs_words[0])
        assert key.kernel == "packed"
        reg = obs_registry.default()
        base = reg.counter("engine_stage_packs_total")
        staged_words = batcher.stage(key, jobs_words)
        assert reg.counter("engine_stage_packs_total") == base
        staged_plain = batcher.stage(key, jobs_plain)
        assert reg.counter("engine_stage_packs_total") == base + 1
        np.testing.assert_array_equal(staged_words.staged.operand,
                                      staged_plain.staged.operand)

    def test_mixed_batch_falls_back_to_pack(self):
        boards = [text_grid.generate(32, 32, seed=50 + i) for i in range(2)]
        jobs = [
            new_job(32, 32, boards[0], gen_limit=1,
                    words=bitpack.pack_words(boards[0])),
            new_job(32, 32, boards[1], gen_limit=1),  # no words
        ]
        key = batcher.bucket_for(jobs[0])
        reg = obs_registry.default()
        base = reg.counter("engine_stage_packs_total")
        staged = batcher.stage(key, jobs)
        assert reg.counter("engine_stage_packs_total") == base + 1
        assert staged.staged.mode == "packed"

    def test_words_results_round_trip_bit_exact(self):
        """A packed-words staging computes the same results as cell
        staging (the engine contract extended to the wire lane)."""
        boards = [text_grid.generate(32, 32, seed=60 + i) for i in range(2)]
        jobs_words = [
            new_job(32, 32, b, gen_limit=9, words=bitpack.pack_words(b))
            for b in boards
        ]
        key = batcher.bucket_for(jobs_words[0])
        results = batcher.complete(
            batcher.dispatch(batcher.stage(key, jobs_words))
        )
        for b, r in zip(boards, results):
            want = oracle.run(b, GameConfig(gen_limit=9))
            np.testing.assert_array_equal(r.grid, want.grid)
            assert r.generations == want.generations
            # Result words retained (packed mode) and consistent.
            assert r.words is not None
            np.testing.assert_array_equal(bitpack.unpack_words(r.words, 32),
                                          r.grid)

    def test_bad_word_shape_rejected(self):
        board = text_grid.generate(32, 32, seed=70)
        with pytest.raises(ValueError, match="word shape"):
            engine.stage_batch(
                [board], [GameConfig(gen_limit=1)],
                padded_shape=(32, 32),
                packed_boards=[np.zeros((32, 2), np.uint32)],
            )


class TestCASPacked:
    def test_packed_payload_round_trip(self, tmp_path):
        from gol_tpu.cache.store import CacheEntry, DiskCAS

        cas = DiskCAS(str(tmp_path))  # packed is the default
        grid = text_grid.generate(48, 48, seed=80)  # width not % 32
        entry = CacheEntry(grid=grid, generations=5, exit_reason="gen_limit")
        cas.put("ab" * 12, entry)
        import os

        assert os.path.exists(cas.packed_path("ab" * 12))
        meta = json.load(open(cas.meta_path("ab" * 12)))
        assert meta["payload"] == "packed"
        assert "grid" not in meta  # the text payload is gone
        got = cas.get("ab" * 12)
        np.testing.assert_array_equal(got.grid, grid)
        assert got.words is not None
        np.testing.assert_array_equal(
            got.words, wire.pack_grid(grid)
        )

    def test_text_entries_still_read_under_packed_config(self, tmp_path):
        """Migration lane: entries written by a text-configured store read
        back on a packed-configured one (and vice versa)."""
        from gol_tpu.cache.store import CacheEntry, DiskCAS

        grid = text_grid.generate(32, 32, seed=81)
        entry = CacheEntry(grid=grid, generations=2, exit_reason="similar")
        DiskCAS(str(tmp_path), payload="text").put("cd" * 12, entry)
        got = DiskCAS(str(tmp_path), payload="packed").get("cd" * 12)
        np.testing.assert_array_equal(got.grid, grid)
        assert got.exit_reason == "similar"
        DiskCAS(str(tmp_path), payload="packed").put("ef" * 12, entry)
        got = DiskCAS(str(tmp_path), payload="text").get("ef" * 12)
        np.testing.assert_array_equal(got.grid, grid)

    def test_corrupt_sidecar_evicts_loudly(self, tmp_path):
        from gol_tpu.cache.store import CacheEntry, DiskCAS

        evicted = []
        cas = DiskCAS(str(tmp_path),
                      on_evict=lambda fp, reason: evicted.append(reason))
        grid = text_grid.generate(32, 32, seed=82)
        cas.put("aa" * 12, CacheEntry(grid=grid, generations=1,
                                      exit_reason="gen_limit"))
        with open(cas.packed_path("aa" * 12), "r+b") as f:
            f.seek(-3, 2)
            f.write(b"\xff\xff\xff")
        assert cas.get("aa" * 12) is None
        assert evicted and "CRC" in evicted[0]
        import os

        assert not os.path.exists(cas.meta_path("aa" * 12))
        assert not os.path.exists(cas.packed_path("aa" * 12))

    def test_packed_entry_words_flow_to_hit_result(self, tmp_path):
        """A disk hit on a packed entry carries words end to end: the
        JobResult a cache hit completes with can answer a packed wire
        response without re-packing."""
        from gol_tpu.cache import ResultCache
        from gol_tpu.serve.scheduler import Scheduler
        from gol_tpu.serve.jobs import DONE

        import time

        board = text_grid.generate(32, 32, seed=83)
        cache1 = ResultCache(cas_dir=str(tmp_path / "cas"))
        s1 = Scheduler(cache=cache1, flush_age=0.01)
        s1.start()
        j1 = s1.submit(new_job(32, 32, board, gen_limit=6))
        for _ in range(2000):
            if j1.state == DONE:
                break
            time.sleep(0.005)
        s1.stop()
        assert j1.state == DONE
        # Fresh memory tier, same CAS: the hit is a disk hit.
        cache2 = ResultCache(cas_dir=str(tmp_path / "cas"))
        s2 = Scheduler(cache=cache2, flush_age=0.01)
        s2.start()
        j2 = s2.submit(new_job(32, 32, board, gen_limit=6))
        s2.stop()
        assert j2.state == DONE and j2.result.cached == "disk"
        assert j2.result.words is not None
        np.testing.assert_array_equal(
            bitpack.unpack_words(j2.result.words, 32), j2.result.grid
        )
        np.testing.assert_array_equal(j2.result.grid, j1.result.grid)


class TestRouterWire:
    def _fleet(self, tmp_path, stub_http=None, **router_kwargs):
        from gol_tpu.fleet.router import RouterServer
        from gol_tpu.fleet.workers import Fleet

        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        for i in range(3):
            w = fleet.attach(f"http://127.0.0.1:{9100 + i}", f"w{i}")
            w.healthy = True
        kwargs = dict(router_kwargs)
        if stub_http is not None:
            kwargs["http"] = stub_http
        router = RouterServer.__new__(RouterServer)
        # Build without binding a socket: these tests exercise routing
        # logic only (the HTTP layer is covered by the rig test below).
        RouterServer.__init__(router, fleet, port=0, **kwargs)
        return router

    def test_packed_forward_is_zero_copy_with_content_type(self, tmp_path):
        sent = {}

        def stub(method, url, body=None, raw=None, timeout=None,
                 headers=None, content_type=None):
            sent["raw"] = raw
            sent["content_type"] = content_type
            sent["kwargs_seen"] = True
            return 202, {"id": "j1", "state": "queued"}

        router = self._fleet(tmp_path, stub_http=stub)
        try:
            board = text_grid.generate(64, 64, seed=90)
            frame = wire.encode_frame({"gen_limit": 3}, grid=board)
            status, payload = router.route_submit(
                frame, content_type=wire.CONTENT_TYPE
            )
            assert status == 202 and payload["worker"]
            assert sent["raw"] is frame  # the SAME buffer: zero-copy
            assert sent["content_type"] == wire.CONTENT_TYPE
        finally:
            router.httpd.server_close()

    def test_text_forward_call_shape_pinned(self, tmp_path):
        """Old-peer compat: the text path must pass NO content_type kwarg
        (stubs and old client signatures keep working byte-identically)."""
        calls = []

        def stub(method, url, body=None, raw=None, timeout=None,
                 headers=None, **extra):
            calls.append(extra)
            return 202, {"id": "j1", "state": "queued"}

        router = self._fleet(tmp_path, stub_http=stub)
        try:
            board = text_grid.generate(32, 32, seed=91)
            body = {"width": 32, "height": 32,
                    "cells": text_grid.encode(board).decode("ascii")}
            status, _ = router.route_submit(json.dumps(body).encode())
            assert status == 202
            assert calls == [{}]  # no content_type, no headers
        finally:
            router.httpd.server_close()

    def test_packed_and_text_share_bucket_placement(self, tmp_path):
        """Format never changes WHERE a bucket lands (bucket routing):
        the same board routes to the same worker either way."""
        owners = []

        def stub(method, url, body=None, raw=None, timeout=None,
                 headers=None, content_type=None):
            owners.append(url)
            return 202, {"id": f"j{len(owners)}", "state": "queued"}

        router = self._fleet(tmp_path, stub_http=stub)
        try:
            board = text_grid.generate(64, 64, seed=92)
            body = {"width": 64, "height": 64,
                    "cells": text_grid.encode(board).decode("ascii")}
            router.route_submit(json.dumps(body).encode())
            router.route_submit(
                wire.encode_frame({}, grid=board),
                content_type=wire.CONTENT_TYPE,
            )
            assert owners[0] == owners[1]
        finally:
            router.httpd.server_close()

    def test_cache_route_packed_fingerprint_deterministic(self, tmp_path):
        labels = []

        def stub(method, url, body=None, raw=None, timeout=None,
                 headers=None, content_type=None):
            return 202, {"id": f"j{len(labels)}", "state": "queued"}

        router = self._fleet(tmp_path, stub_http=stub, cache_route=True)
        try:
            board = text_grid.generate(64, 64, seed=93)
            frame = wire.encode_frame({"gen_limit": 3}, grid=board)
            from gol_tpu.cache.fingerprint import packed_body_fingerprint

            fp1 = packed_body_fingerprint(frame)
            fp2 = packed_body_fingerprint(
                wire.encode_frame({"gen_limit": 3}, grid=board)
            )
            assert fp1 == fp2  # deterministic across resends
            other = packed_body_fingerprint(
                wire.encode_frame({"gen_limit": 4}, grid=board)
            )
            assert other != fp1  # answer-changing axes change the key
            # QoS fields never enter the key (body_fingerprint's rule —
            # a higher-priority repeat must land on the SAME owner).
            qos = packed_body_fingerprint(wire.encode_frame(
                {"gen_limit": 3, "priority": 5, "deadline_s": 10.5},
                grid=board,
            ))
            assert qos == fp1
            status, _ = router.route_submit(
                frame, content_type=wire.CONTENT_TYPE
            )
            assert status == 202
            assert router.registry.counter("jobs_cache_routed_total") == 1
        finally:
            router.httpd.server_close()

    def test_router_415_for_unknown_family_and_version(self, tmp_path):
        router = self._fleet(tmp_path)
        try:
            status, payload = router.route_submit(
                b"??", content_type="application/x-gol-packed-v9"
            )
            assert status == 415
            frame = bytearray(
                wire.encode_frame({}, grid=np.zeros((32, 32), np.uint8))
            )
            struct.pack_into("<H", frame, 4, wire.VERSION + 1)
            with pytest.raises(wire.UnsupportedWire):
                router.route_submit(bytes(frame),
                                    content_type=wire.CONTENT_TYPE)
        finally:
            router.httpd.server_close()

    def test_full_rig_packed_round_trip(self, tmp_path):
        """Real workers behind a real router: packed submit in, packed
        result relay out, byte-identical to the text lane."""
        from gol_tpu.fleet.router import RouterServer
        from gol_tpu.fleet.workers import Fleet

        workers = {}
        for wid in ("w0", "w1"):
            srv = GolServer(port=0, flush_age=0.01)
            srv.start()
            workers[wid] = srv
        fleet = Fleet(str(tmp_path / "fleet"))
        for wid, srv in workers.items():
            fleet.attach(srv.url, wid)
        router = RouterServer(fleet, port=0)
        router.start()
        try:
            base = router.url
            board = text_grid.generate(64, 64, seed=94)
            st, p_p = _submit_packed(base, board, gen_limit=10)
            assert st == 202 and "worker" in p_p
            st, p_t = _submit_text(base, board, gen_limit=10)
            assert st == 202
            for jid in (p_p["id"], p_t["id"]):
                assert _wait_done(base, jid)
            want = oracle.run(board, GameConfig(gen_limit=10))
            for jid in (p_p["id"], p_t["id"]):
                _, grid_t = _result_text(base, jid)
                meta, grid_p = _result_packed(base, jid)
                np.testing.assert_array_equal(grid_t, want.grid)
                np.testing.assert_array_equal(grid_p, want.grid)
                assert meta["generations"] == want.generations
        finally:
            router.shutdown(cascade=False)
            for srv in workers.values():
                srv.shutdown()


class _OldServer(BaseHTTPRequestHandler):
    """A pre-wire server: JSON only — a packed frame fails its JSON parse
    with a 400, exactly what a PR-10 `gol serve` answers."""

    protocol_version = "HTTP/1.1"
    store = {}

    def log_message(self, *a):  # noqa: A002
        pass

    def _reply(self, code, payload):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if code >= 400:
            self.send_header("Connection", "close")
            self.close_connection = True
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        raw = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        try:
            body = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        jid = f"old{len(_OldServer.store)}"
        _OldServer.store[jid] = body
        self._reply(202, {"id": jid, "state": "queued"})

    def do_GET(self):
        if self.path.startswith("/jobs/"):
            self._reply(200, {"state": "done"})
        elif self.path.startswith("/result/"):
            jid = self.path[len("/result/"):]
            body = _OldServer.store[jid]
            self._reply(200, {
                "id": jid, "generations": 0, "exit_reason": "gen_limit",
                "width": body["width"], "height": body["height"],
                "grid": body["cells"],
            })
        else:
            self._reply(404, {"error": "?"})


class TestCliWire:
    def test_packed_client_degrades_against_old_server(self, tmp_path,
                                                       capsys, monkeypatch):
        from gol_tpu import cli

        _OldServer.store = {}
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _OldServer)
        httpd.daemon_threads = True
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}"
            board = text_grid.generate(32, 32, seed=95)
            inp = tmp_path / "in.txt"
            text_grid.write_grid(str(inp), board)
            monkeypatch.chdir(tmp_path)
            rc = cli.main([
                "submit", "32", "32", str(inp), "--server", url,
                "--wire", "packed", "--gen-limit", "0",
                "--poll-interval", "0.01",
            ])
            assert rc == 0
            err = capsys.readouterr().err
            assert "does not accept the packed wire format" in err
            # ONE logged downgrade, then text — and the result landed.
            assert err.count("retrying as text") == 1
            out = text_grid.read_grid(str(inp) + ".out", 32, 32)
            np.testing.assert_array_equal(out, board)
        finally:
            httpd.shutdown()

    def test_packed_client_against_new_server_byte_identical(self, tmp_path,
                                                             monkeypatch):
        from gol_tpu import cli

        srv = GolServer(port=0, flush_age=0.01)
        srv.start()
        try:
            board = text_grid.generate(32, 32, seed=96)
            inp = tmp_path / "in.txt"
            text_grid.write_grid(str(inp), board)
            monkeypatch.chdir(tmp_path)
            for wire_mode, suffix in (("packed", "p"), ("text", "t")):
                outdir = tmp_path / suffix
                rc = cli.main([
                    "submit", "32", "32", str(inp), "--server", srv.url,
                    "--wire", wire_mode, "--gen-limit", "8",
                    "--poll-interval", "0.01", "--output-dir", str(outdir),
                ])
                assert rc == 0
            packed_out = (tmp_path / "p" / "in.txt.out").read_bytes()
            text_out = (tmp_path / "t" / "in.txt.out").read_bytes()
            assert packed_out == text_out  # byte-identical files
        finally:
            srv.shutdown()
