"""The observability subsystem (gol_tpu/obs): tracing, registry, flight
recorder, profiler guard, and the trace-report renderer.

The load-bearing assertions:

- with tracing DISABLED (the default), ``trace.span`` returns a module
  singleton — zero allocation, nothing recorded — so the engine's hot
  paths pay one attribute check;
- a traced serve batch round-trips into Chrome trace JSON with well-formed
  ``ph:"X"`` events, monotonic timestamps, and correct thread/span nesting
  (ISSUE 4 acceptance);
- the serve /metrics contracts survived the registry hoist byte-for-byte;
- the flight recorder's dumps are parseable JSONL from crash, trigger, and
  SIGUSR1 paths;
- the profiler guard never lets a capture failure kill a run, and never
  leaves a torn capture behind a crashed body.
"""

import json
import os
import signal
import time
import urllib.request

import numpy as np
import pytest

from gol_tpu import engine
from gol_tpu.config import GameConfig
from gol_tpu.io import text_grid
from gol_tpu.obs import profiler, recorder, registry, report, trace
from gol_tpu.resilience.retry import RetryPolicy
from gol_tpu.serve import batcher
from gol_tpu.serve.jobs import DONE, new_job
from gol_tpu.serve.metrics import Metrics


def _reset_tracer():
    """Off, empty, and back at the DEFAULT ring size: a test that shrank
    the ring (test_ring_is_bounded_and_counts_drops) must not leave a
    4-slot ring for every later traced-session test — with job flow events
    in the ring too, a tiny leftover ring evicts the very spans those
    tests assert on."""
    trace.enable(ring_size=trace._DEFAULT_RING)
    trace.disable()
    trace.clear()


@pytest.fixture(autouse=True)
def _clean_obs():
    """Every test starts and ends with tracing off, recorder disarmed, and a
    fresh global registry — obs state is process-global by design."""
    _reset_tracer()
    recorder.uninstall()
    registry.reset_default()
    yield
    _reset_tracer()
    recorder.uninstall()
    registry.reset_default()


class TestTracer:
    def test_disabled_span_is_shared_noop_singleton(self):
        a = trace.span("x", big=1)
        b = trace.span("y")
        assert a is b is trace._NOOP  # zero allocation on the disabled path
        with a as handle:
            assert handle is None
        assert trace.snapshot() == []

    def test_spans_record_name_duration_attrs_nesting(self):
        trace.enable()
        with trace.span("outer", gen=3):
            time.sleep(0.002)
            with trace.span("inner"):
                time.sleep(0.001)
        spans = trace.snapshot()
        assert [s["name"] for s in spans] == ["inner", "outer"]  # finish order
        inner, outer = spans
        assert outer["depth"] == 0 and inner["depth"] == 1
        assert outer["attrs"] == {"gen": 3}
        assert outer["duration_s"] >= inner["duration_s"] > 0
        # The child ran inside the parent's window.
        assert outer["start_s"] <= inner["start_s"]
        assert (inner["start_s"] + inner["duration_s"]
                <= outer["start_s"] + outer["duration_s"] + 1e-6)

    def test_exception_inside_span_is_recorded_and_depth_restored(self):
        trace.enable()
        with pytest.raises(RuntimeError):
            with trace.span("boom"):
                raise RuntimeError("x")
        (span,) = trace.snapshot()
        assert span["attrs"]["error"] == "RuntimeError"
        with trace.span("after"):
            pass
        assert trace.snapshot()[-1]["depth"] == 0  # stack unwound

    def test_ring_is_bounded_and_counts_drops(self):
        trace.enable(ring_size=4)
        for i in range(10):
            with trace.span(f"s{i}"):
                pass
        spans = trace.snapshot()
        assert len(spans) == 4
        assert [s["name"] for s in spans] == ["s6", "s7", "s8", "s9"]
        assert trace.tracer().dropped() == 6

    def test_wall_anchor_taken_once_at_enable(self):
        trace.enable()
        anchor = trace.tracer().anchor_unix_ns
        assert anchor > 0
        trace.enable()  # idempotent: the anchor must not move
        assert trace.tracer().anchor_unix_ns == anchor


class TestChromeExport:
    def test_serve_batch_roundtrip_well_formed(self, tmp_path):
        """A recorded serve batch exports as Chrome trace JSON: ph:"X"
        events, monotonic timestamps, correct thread/span nesting."""
        trace.enable()
        boards = [text_grid.generate(32, 32, seed=s) for s in (1, 2)]
        jobs = [new_job(32, 32, b, gen_limit=8) for b in boards]
        key = batcher.bucket_for(jobs[0])
        batcher.run_batch(key, jobs)
        path = trace.export_chrome(str(tmp_path / "t.json"))
        doc = json.load(open(path))
        events = doc["traceEvents"]
        assert events, "no events exported"
        assert all(e["ph"] == "X" for e in events)
        assert all(e["dur"] >= 0 for e in events)
        ts = [e["ts"] for e in events]
        assert ts == sorted(ts)  # monotonic
        by_name = {e["name"]: e for e in events}
        outer = by_name["batcher.run_batch"]
        inner = by_name["engine.simulate_batch"]
        assert outer["tid"] == inner["tid"]  # same thread
        assert outer["args"]["depth"] == 0 and inner["args"]["depth"] == 1
        # Nesting: the engine span lies within the batcher span's window.
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1.0
        assert outer["args"]["bucket"] == key.label()
        assert doc["otherData"]["anchor_unix_ns"] > 0

    def test_traced_server_session_two_buckets(self, tmp_path):
        """ISSUE 4 acceptance: a traced serve session with >= 2 padding
        buckets exports batch spans for both, and GET /debug/trace serves a
        live snapshot."""
        from gol_tpu.serve.server import GolServer

        trace.enable()
        srv = GolServer(port=0, flush_age=0.01)
        srv.start()
        try:
            jobs = [
                srv.scheduler.submit(new_job(32, 32,
                                             text_grid.generate(32, 32, seed=1),
                                             gen_limit=6)),
                srv.scheduler.submit(new_job(30, 30,
                                             text_grid.generate(30, 30, seed=2),
                                             gen_limit=6)),
            ]
            deadline = time.perf_counter() + 60
            while time.perf_counter() < deadline:
                if all(j.state == DONE for j in jobs):
                    break
                time.sleep(0.01)
            assert all(j.state == DONE for j in jobs)
            with urllib.request.urlopen(f"{srv.url}/debug/trace", timeout=30) as r:
                snap = json.loads(r.read())
            assert snap["enabled"] is True
            live_batches = [s for s in snap["spans"]
                            if s["name"] == "serve.batch"]
            assert len(live_batches) >= 2
            assert "counters" in snap["registry"]
        finally:
            srv.shutdown()
        path = trace.export_chrome(str(tmp_path / "serve.json"))
        events = json.load(open(path))["traceEvents"]
        batch_buckets = {e["args"]["bucket"] for e in events
                         if e["name"] == "serve.batch"}
        assert len(batch_buckets) == 2  # one lane per padding bucket
        # Spans export as ph:"X"; job lifecycles additionally export as
        # flow events (ph s/t/f) tying each job to its batch span (ISSUE 7).
        assert {e["ph"] for e in events} <= {"X", "s", "t", "f"}
        assert all("dur" in e for e in events if e["ph"] == "X")
        finished = {e["id"] for e in events if e["ph"] == "f"}
        assert finished == {j.id for j in jobs}


class TestRegistry:
    def test_quantile_and_median_rules(self):
        # quantile: round-based nearest rank (the serving histograms' rule).
        assert registry.quantile([1, 2, 3, 4, 5], 0.5) == 3
        assert registry.quantile([5, 1], 0.95) == 5
        assert registry.quantile([], 0.5) is None
        # median: sorted[n // 2] (the measurement protocol's upper median) —
        # distinct from quantile(..., 0.5) on counts ≡ 2 mod 4.
        assert registry.median([3, 1, 2]) == 2
        assert registry.median([1, 2, 3, 4, 5, 6]) == 4
        assert registry.quantile([1, 2, 3, 4, 5, 6], 0.5) == 3  # banker's round
        with pytest.raises(ValueError):
            registry.median([])

    def test_serve_metrics_facade_byte_stable(self):
        """The hoist of the PR 2 registry into obs must not move a byte of
        either /metrics contract."""
        m = Metrics()
        m.inc("jobs_accepted_total")
        m.inc("jobs_accepted_total")
        m.set_gauge("queue_depth", 3)
        for v in (0.25, 0.5, 0.75):
            m.observe("run_latency_seconds", v)
        assert m.counter("jobs_accepted_total") == 2
        snap = m.snapshot()
        assert snap["counters"] == {"jobs_accepted_total": 2}
        assert snap["gauges"] == {"queue_depth": 3.0}
        assert snap["histograms"]["run_latency_seconds"] == {
            "count": 3, "sum": 1.5, "p50": 0.5, "p95": 0.75, "p99": 0.75,
        }
        assert m.prometheus() == (
            "# TYPE gol_serve_jobs_accepted_total counter\n"
            "gol_serve_jobs_accepted_total 2\n"
            "# TYPE gol_serve_queue_depth gauge\n"
            "gol_serve_queue_depth 3\n"
            "# TYPE gol_serve_run_latency_seconds summary\n"
            'gol_serve_run_latency_seconds{quantile="0.5"} 0.5\n'
            'gol_serve_run_latency_seconds{quantile="0.95"} 0.75\n'
            'gol_serve_run_latency_seconds{quantile="0.99"} 0.75\n'
            "gol_serve_run_latency_seconds_sum 1.5\n"
            "gol_serve_run_latency_seconds_count 3\n"
        )

    def test_engine_feeds_default_registry(self):
        board = text_grid.generate(16, 16, seed=3)
        result = engine.simulate(board, GameConfig(gen_limit=5))
        reg = registry.default()
        assert reg.counter("engine_runs_total") == 1
        assert reg.counter("engine_generations_total") == result.generations
        engine.simulate_batch([board], GameConfig(gen_limit=5))
        assert reg.counter("engine_batches_total") == 1
        assert reg.counter("engine_boards_total") == 1

    def test_retry_attempts_counted(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("connection reset by peer")
            return "ok"

        policy = RetryPolicy(attempts=3, base_delay=0.0)
        assert policy.call(flaky) == "ok"
        assert registry.default().counter("retry_attempts_total") == 2

    def test_checkpoint_outcomes_counted(self, tmp_path):
        from gol_tpu.resilience.checkpoint import CheckpointManager, PayloadCodec

        def write(path, state):
            np.save(path + ".npy", state)
            os.replace(path + ".npy", path)

        mgr = CheckpointManager(
            str(tmp_path),
            height=8, width=8,
            codec=PayloadCodec(format="npy", suffix=".npy", write=write,
                               read=lambda p: np.load(p)),
        )
        state = np.zeros((8, 8), np.uint8)
        mgr.save(state, 4, 1)
        restored = mgr.restore()
        assert restored is not None
        reg = registry.default()
        assert reg.counter("checkpoint_saves_total") == 1
        assert reg.counter("checkpoint_restores_total") == 1

    def test_halo_bytes_accounted_at_trace_time(self):
        from gol_tpu.parallel.mesh import make_mesh

        board = text_grid.generate(16, 16, seed=5)
        engine.simulate(board, GameConfig(gen_limit=3), mesh=make_mesh(2, 2))
        reg = registry.default()
        assert reg.counter("halo_exchange_sites_traced_total") >= 1
        assert reg.snapshot()["gauges"].get("halo_exchange_bytes", 0) > 0

    def test_tuner_trials_counted(self):
        from gol_tpu.tune import measure

        result = measure.run_engine_search(
            16, 32, GameConfig(gen_limit=2), iters=1, quick=True,
        )
        assert registry.default().counter("tuner_trials_total") == len(
            result.trials
        )


class TestRecorder:
    def test_trigger_writes_parseable_jsonl(self, tmp_path):
        trace.enable()
        with trace.span("work", step=1):
            pass
        registry.default().inc("engine_runs_total")
        recorder.install(str(tmp_path))
        path = recorder.trigger("unit-test")
        assert path is not None and os.path.exists(path)
        records = recorder.read_dump(path)
        kinds = [r["record"] for r in records]
        assert kinds[0] == "header" and kinds[-1] == "registry"
        assert records[0]["reason"] == "unit-test"
        assert any(r["record"] == "span" and r["name"] == "work"
                   for r in records)
        assert records[-1]["counters"]["engine_runs_total"] == 1

    def test_unarmed_trigger_is_none(self):
        assert recorder.trigger("nothing armed") is None

    def test_sigusr1_dumps_without_dying(self, tmp_path):
        trace.enable()
        with trace.span("alive"):
            pass
        recorder.install(str(tmp_path))
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.perf_counter() + 10
        dumps = []
        while time.perf_counter() < deadline and not dumps:
            dumps = [f for f in os.listdir(tmp_path)
                     if f.startswith("flight-")]
            time.sleep(0.01)
        assert dumps, "SIGUSR1 produced no dump"
        records = recorder.read_dump(str(tmp_path / dumps[0]))
        assert records[0]["reason"] == "SIGUSR1"

    def test_reinstall_after_uninstall_does_not_self_chain(self, tmp_path):
        """Review regression: install → uninstall → install must not chain
        sys.excepthook to itself (the next uncaught exception would recurse
        through the hook, dumping files until RecursionError)."""
        import sys

        recorder.install(str(tmp_path / "a"))
        hook_after_first = sys.excepthook
        recorder.uninstall()
        recorder.install(str(tmp_path / "b"))
        assert sys.excepthook is hook_after_first
        assert recorder._prev_excepthook is not recorder._excepthook
        # The re-armed recorder dumps into the NEW directory.
        assert recorder.trigger("rearm") is not None
        assert [f for f in os.listdir(tmp_path / "b")
                if f.startswith("flight-")]

    def test_excepthook_dumps_on_crash(self, tmp_path):
        trace.enable()
        recorder.install(str(tmp_path))
        # Drive the hook directly (raising through pytest would fail the
        # test); the chained previous hook is exercised too.
        seen = {}
        prev, recorder._prev_excepthook = (
            recorder._prev_excepthook,
            lambda t, e, tb: seen.update(type=t),
        )
        try:
            recorder._excepthook(RuntimeError, RuntimeError("boom"), None)
        finally:
            recorder._prev_excepthook = prev
        assert seen["type"] is RuntimeError
        dumps = [f for f in os.listdir(tmp_path) if f.startswith("flight-")]
        assert len(dumps) == 1
        records = recorder.read_dump(str(tmp_path / dumps[0]))
        assert "crash: RuntimeError: boom" in records[0]["reason"]


class TestProfilerGuard:
    def test_disabled_capture_is_noop(self):
        with profiler.capture(None) as started:
            assert started is False

    def test_start_failure_degrades_to_unprofiled(self, tmp_path, monkeypatch):
        import jax

        def boom(*a, **k):
            raise RuntimeError("no profiler backend")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        ran = {}
        with profiler.capture(str(tmp_path / "prof")) as started:
            assert started is False
            ran["body"] = True
        assert ran["body"]  # the run proceeded

    def test_crashing_body_sweeps_torn_capture(self, tmp_path, monkeypatch):
        import jax

        prof = tmp_path / "prof"

        def fake_start(d, *a, **k):
            os.makedirs(os.path.join(d, "plugins", "profile"), exist_ok=True)

        monkeypatch.setattr(jax.profiler, "start_trace", fake_start)
        monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
        with pytest.raises(RuntimeError):
            with profiler.capture(str(prof)):
                raise RuntimeError("mid-capture crash")
        # The torn capture was swept: no partial profile masquerading as
        # evidence (the empty/absent dir is the contract).
        assert not prof.exists() or os.listdir(prof) == []

    def test_preexisting_captures_survive_a_sweep(self, tmp_path, monkeypatch):
        import jax

        prof = tmp_path / "prof"
        os.makedirs(prof / "earlier_run")
        monkeypatch.setattr(jax.profiler, "start_trace", lambda *a, **k: None)
        monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
        with pytest.raises(RuntimeError):
            with profiler.capture(str(prof)):
                raise RuntimeError("crash")
        assert (prof / "earlier_run").exists()  # not ours to sweep

    def test_fence_handles_nested_and_host_values(self):
        import jax.numpy as jnp

        profiler.fence(jnp.zeros((4,)), (1, [jnp.ones(2), "x"]), None)


class TestReport:
    def test_render_chrome_export(self, tmp_path):
        trace.enable()
        with trace.span("cli.execution"):
            with trace.span("engine.segment", gen0=1):
                pass
        path = trace.export_chrome(str(tmp_path / "t.json"))
        out = report.render(path)
        assert "per-phase" in out
        assert "cli.execution" in out and "engine.segment" in out
        assert "p50_ms" in out and "gap" in out

    def test_render_flight_dump_with_registry(self, tmp_path):
        trace.enable()
        with trace.span("checkpoint.save", generation=8):
            pass
        registry.default().inc("checkpoint_saves_total")
        recorder.install(str(tmp_path))
        path = recorder.trigger("test")
        out = report.render(path)
        assert "checkpoint.save" in out
        assert "checkpoint_saves_total = 1" in out
        assert "reason=test" in out

    def test_render_empty_file(self, tmp_path):
        p = tmp_path / "empty.jsonl"
        p.write_text("")
        assert "(no spans recorded)" in report.render(str(p))
