"""Tier-1 lint gates: source-level rules the advisor rounds keep re-fixing.

Advisor r4 flagged raw ``sys.stderr.write`` calls in library code (the kernel
ladder's demotion messages); the resilience pass routed them through the
``logging`` module (``gol_tpu.engine`` logger, stderr handler attached by the
entry points — platform_env.configure_cli_logging). This test keeps that
regression class from coming back: library modules must log, never write the
stream directly — an embedder owns routing, and a handler owns truncation.
"""

import pathlib

import gol_tpu

_LIBRARY_ROOT = pathlib.Path(gol_tpu.__file__).parent
_FORBIDDEN = "sys.stderr.write"


def test_no_raw_stderr_write_in_library_code():
    offenders = []
    for path in sorted(_LIBRARY_ROOT.rglob("*.py")):
        for lineno, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), start=1
        ):
            code = line.split("#", 1)[0]  # prose may name the rule; code may not
            if _FORBIDDEN in code:
                offenders.append(f"{path.relative_to(_LIBRARY_ROOT)}:{lineno}")
    assert not offenders, (
        f"raw {_FORBIDDEN} in gol_tpu/ library code (route through "
        f"logging.getLogger(__name__) instead; see platform_env."
        f"configure_cli_logging): {offenders}"
    )
