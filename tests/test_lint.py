"""Tier-1 lint gates: source-level rules the advisor rounds keep re-fixing.

Advisor r4 flagged raw ``sys.stderr.write`` calls in library code (the kernel
ladder's demotion messages); the resilience pass routed them through the
``logging`` module (``gol_tpu.engine`` logger, stderr handler attached by the
entry points — platform_env.configure_cli_logging). This test keeps that
regression class from coming back: library modules must log, never write the
stream directly — an embedder owns routing, and a handler owns truncation.
"""

import pathlib

import gol_tpu

_LIBRARY_ROOT = pathlib.Path(gol_tpu.__file__).parent
_FORBIDDEN = "sys.stderr.write"


def _offenders(root: pathlib.Path, needle: str) -> list[str]:
    out = []
    for path in sorted(root.rglob("*.py")):
        for lineno, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), start=1
        ):
            code = line.split("#", 1)[0]  # prose may name the rule; code may not
            if needle in code:
                out.append(f"{path.relative_to(root)}:{lineno}")
    return out


def test_no_raw_stderr_write_in_library_code():
    offenders = _offenders(_LIBRARY_ROOT, _FORBIDDEN)
    assert not offenders, (
        f"raw {_FORBIDDEN} in gol_tpu/ library code (route through "
        f"logging.getLogger(__name__) instead; see platform_env."
        f"configure_cli_logging): {offenders}"
    )


def test_no_raw_stderr_write_in_parallel():
    """The gol_tpu/parallel/ pin of the rule above (ADVICE r5:
    ``choose_mesh_shape``'s width-cap fallback once wrote its warning via
    raw ``sys.stderr.write`` from library code — it now rides
    ``warnings.warn(RuntimeWarning)`` so embedders can filter and repeated
    ``make_mesh`` calls dedupe per call site; tests/test_engine.py pins the
    category). The whole-tree test already covers this subtree; this one
    exists so a future split of the library root cannot silently drop the
    mesh-layer coverage the finding was about."""
    offenders = _offenders(_LIBRARY_ROOT / "parallel", _FORBIDDEN)
    assert not offenders, (
        f"raw {_FORBIDDEN} in gol_tpu/parallel/ (choose_mesh_shape's "
        f"fallback warning must ride warnings.warn/logging): {offenders}"
    )


def test_no_wall_clock_in_serve_latency_paths():
    """``time.time()`` is banned in gol_tpu/serve/: every latency sample and
    dispatch-age decision there must come from ``time.perf_counter()``. The
    wall clock steps under NTP (backwards included), which turns queue-age
    math into negative waits and p99 latency into fiction. The journal
    deliberately stores no timestamps at all, so nothing in the package has
    a legitimate wall-clock need."""
    offenders = _offenders(_LIBRARY_ROOT / "serve", "time.time(")
    assert not offenders, (
        "wall-clock time.time() in gol_tpu/serve/ (use time.perf_counter() "
        f"for every latency/age path): {offenders}"
    )


def test_no_wall_clock_in_obs():
    """Same rule for gol_tpu/obs/: span durations, histogram samples, and
    report math are ``time.perf_counter()`` only — an observability layer
    whose own numbers step under NTP would poison every consumer at once.
    The rglob below covers the WHOLE package, emphatically including the
    SLO engine's rolling windows and the dispatch-gap sampler's tick deltas
    (obs/slo.py, obs/sampler.py): a stepped clock there would fire — or
    suppress — a burn-rate page, and with ``--slo-shed`` turn a clock
    adjustment into load shedding.
    The sanctioned wall-clock reads are the per-process alignment anchors,
    taken via ``time.time_ns()`` — at ``trace.enable()`` (the tracer's) and
    per segment header in ``history.HistoryWriter`` (the metrics ring's) —
    outside this needle set on purpose, exported as metadata, and never
    part of any duration, rate, or timestamp arithmetic (gol_tpu/obs/
    trace.py and history.py document them; fleettrace.py consumes them
    only to align axes ACROSS processes, never within one)."""
    for needle in ("time.time(", "datetime.now"):
        offenders = _offenders(_LIBRARY_ROOT / "obs", needle)
        assert not offenders, (
            f"wall-clock {needle} in gol_tpu/obs/ (use time.perf_counter() "
            f"for every span/sample; the one alignment anchor is "
            f"time.time_ns at trace.enable): {offenders}"
        )


def test_no_wall_clock_in_pipeline():
    """Same rule for gol_tpu/pipeline/: the async writer's hidden-time and
    stall accounting (``checkpoint_write_hidden_seconds``,
    ``pipeline_stalls_total``) and every handoff wait are
    ``time.perf_counter()`` only — a stepped wall clock would turn
    "how much I/O did compute hide" into a negative number."""
    for needle in ("time.time(", "datetime.now"):
        offenders = _offenders(_LIBRARY_ROOT / "pipeline", needle)
        assert not offenders, (
            f"wall-clock {needle} in gol_tpu/pipeline/ (use "
            f"time.perf_counter() for every overlap/stall measurement): "
            f"{offenders}"
        )


def test_no_wall_clock_in_tune():
    """Same rule for gol_tpu/tune/, where the stakes are higher still: a
    wall-clock step during a timed trial silently corrupts the *persisted*
    plan — every later run on the machine then executes the wrong
    configuration. Trial timing is ``time.perf_counter()`` only."""
    for needle in ("time.time(", "datetime.now"):
        offenders = _offenders(_LIBRARY_ROOT / "tune", needle)
        assert not offenders, (
            f"wall-clock {needle} in gol_tpu/tune/ (use time.perf_counter() "
            f"for every trial timing): {offenders}"
        )


def test_no_wall_clock_in_fleet():
    """Same rule for gol_tpu/fleet/: boot/health deadlines, drain
    timeouts, and respawn supervision all subtract clock readings — a
    stepped wall clock would declare a healthy worker dead (and SIGKILL
    it) or hang a drain. ``time.perf_counter()`` only."""
    for needle in ("time.time(", "datetime.now"):
        offenders = _offenders(_LIBRARY_ROOT / "fleet", needle)
        assert not offenders, (
            f"wall-clock {needle} in gol_tpu/fleet/ (use "
            f"time.perf_counter() for every deadline/health path): "
            f"{offenders}"
        )


def test_no_wall_clock_in_lease_or_replicate():
    """The gol_tpu/fleet/ pin of the rule above for the PR-16 control
    plane (the whole-tree fleet test already covers both files; this one
    exists so a future split of the coordination layer out of fleet/
    cannot silently drop it). fleet/lease.py holds NO clocks BY DESIGN —
    leadership is a kernel flock, not a TTL: any timestamp-based lease
    would need wall-clock comparisons ACROSS processes, which step under
    NTP and turn two concurrent 'leaders' into a split brain.
    fleet/replicate.py persists floors/breaker state with NO timestamps
    for the same reason — perf_counter anchors do not compare across
    processes, so durable coordination state must carry no time at
    all."""
    for name in ("lease.py", "replicate.py"):
        path = _LIBRARY_ROOT / "fleet" / name
        assert path.exists(), f"gol_tpu/fleet/{name} moved; update this pin"
        source = path.read_text(encoding="utf-8")
        for needle in ("time.time(", "datetime.now", "perf_counter("):
            hits = [i + 1 for i, line in enumerate(source.splitlines())
                    if needle in line and not line.lstrip().startswith("#")]
            assert not hits, (
                f"clock call {needle} in gol_tpu/fleet/{name}:{hits} — "
                "the control plane is clock-free by design (flock "
                "leases, not TTLs; timestamp-free durable state)"
            )


def test_no_wall_clock_in_cache():
    """Same rule for gol_tpu/cache/: the result cache sits on the serve
    admission path (consult-before-enqueue) and feeds the same latency
    series — any age/latency accounting it ever grows must be
    ``time.perf_counter()`` only, and nothing in a content-addressed store
    has a legitimate wall-clock need (entries are keyed by content, not
    mtime)."""
    for needle in ("time.time(", "datetime.now"):
        offenders = _offenders(_LIBRARY_ROOT / "cache", needle)
        assert not offenders, (
            f"wall-clock {needle} in gol_tpu/cache/ (use "
            f"time.perf_counter() for any latency path): {offenders}"
        )


def test_no_wall_clock_in_chaos():
    """Same rule for gol_tpu/chaos/: a ChaosPlan's injected delays and the
    proxy's per-exchange timing sit INSIDE the latency measurements every
    defense (breaker slow-call windows, deadline budgets) is judged by —
    a stepped wall clock there would skew the very fault the test meant
    to inject. ``time.perf_counter``/``time.sleep`` only."""
    for needle in ("time.time(", "datetime.now"):
        offenders = _offenders(_LIBRARY_ROOT / "chaos", needle)
        assert not offenders, (
            f"wall-clock {needle} in gol_tpu/chaos/ (use "
            f"time.perf_counter()/time.sleep() for every injected "
            f"delay): {offenders}"
        )


def test_bit_packing_only_in_bitpack():
    """``np.packbits``/``np.unpackbits`` are banned everywhere in gol_tpu/
    except ``io/bitpack.py`` — the ONE copy of the bit-order rule ("bit j
    of word w = column 32w+j"). The rule now has FOUR would-be
    re-implementation sites (engine staging, the CAS ts lane, the tuner's
    packed-state trials, and the wire codec), and a change reaching only
    some of them would silently scramble columns in the rest: a packed
    wire submit would decode to a different board than the text form of
    the same bytes, poisoning results and cache entries alike."""
    for needle in ("np.packbits", "np.unpackbits"):
        offenders = [
            o for o in _offenders(_LIBRARY_ROOT, needle)
            if not o.startswith(str(pathlib.Path("io") / "bitpack.py"))
        ]
        assert not offenders, (
            f"{needle} outside gol_tpu/io/bitpack.py (route through "
            f"bitpack.pack_words/unpack_words — the bit-order rule must "
            f"stay single-copy): {offenders}"
        )


def test_no_wall_clock_in_engine():
    """Same rule for the engine module itself, which PR 6 made part of the
    serve hot path (the batched/ring runners and their staging live there):
    the dispatch-gap and occupancy numbers built on top of it are only
    meaningful over a monotonic clock. The serve/ rule already covers
    gol_tpu/serve/resident.py recursively; this pins the engine side."""
    for needle in ("time.time(", "datetime.now"):
        offenders = _offenders(_LIBRARY_ROOT, needle)
        offenders = [o for o in offenders if o.startswith("engine.py")]
        assert not offenders, (
            f"wall-clock {needle} in gol_tpu/engine.py (use "
            f"time.perf_counter() on every serving path): {offenders}"
        )


def test_no_wall_clock_in_storage_lifecycle_modules():
    """Same rule for the storage-lifecycle modules in gol_tpu/resilience/:
    the disk-pressure watchdog's transition decisions (diskguard.py) are
    pure byte comparisons stamped with ``time.perf_counter`` only — a
    stepped wall clock must never flip admission on or off — and the
    filesystem shim (fsio.py) has no clock at all (exhaustion is about
    bytes, not time). The CAS's atime-LRU ledger is covered by the
    existing gol_tpu/cache/ ban (eviction recency is the injectable
    perf_counter clock; cold entries fall back to file-mtime ORDERING,
    never clock arithmetic), and serve/compaction.py by the serve/ ban.
    Scoped to the two new files rather than all of resilience/ because
    checkpoint.py's manifest ``created_unix`` is a sanctioned
    metadata-only wall stamp (never part of validity or ordering)."""
    for module in ("diskguard.py", "fsio.py"):
        for needle in ("time.time(", "datetime.now"):
            offenders = _offenders(_LIBRARY_ROOT / "resilience", needle)
            offenders = [o for o in offenders if o.startswith(module)]
            assert not offenders, (
                f"wall-clock {needle} in gol_tpu/resilience/{module} (use "
                f"time.perf_counter() for any timing path): {offenders}"
            )


def test_no_wall_clock_in_sparse():
    """Same rule for gol_tpu/sparse/: the sparse engine sits on the serve
    dispatch path (sparse buckets ride the scheduler) and its run stats
    feed the serving work series — any timing it ever grows must be
    ``time.perf_counter()`` only, like every other serving-path package."""
    for needle in ("time.time(", "datetime.now"):
        offenders = _offenders(_LIBRARY_ROOT / "sparse", needle)
        assert not offenders, (
            f"wall-clock {needle} in gol_tpu/sparse/ (use "
            f"time.perf_counter() for any timing path): {offenders}"
        )


def test_no_wall_clock_in_shard():
    """Same rule for gol_tpu/shard/: super-step barriers, halo retry
    backoff, and recovery probing are all interval arithmetic — a
    wall-clock jump (NTP step, suspend) must never fake a barrier
    timeout or age a checkpoint. ``time.perf_counter()`` only."""
    for needle in ("time.time(", "datetime.now"):
        offenders = _offenders(_LIBRARY_ROOT / "shard", needle)
        assert not offenders, (
            f"wall-clock {needle} in gol_tpu/shard/ (use "
            f"time.perf_counter() for any timing path): {offenders}"
        )


def test_no_wall_clock_in_macro():
    """Same rule for gol_tpu/macro/: macro jobs ride the same scheduler
    lanes as sparse ones and the advance memo feeds the same CAS — and a
    content-addressed engine has no legitimate wall-clock need at all
    (node identity is content, memo keys carry no time-of-day)."""
    for needle in ("time.time(", "datetime.now"):
        offenders = _offenders(_LIBRARY_ROOT / "macro", needle)
        assert not offenders, (
            f"wall-clock {needle} in gol_tpu/macro/ (use "
            f"time.perf_counter() for any timing path): {offenders}"
        )
