"""The sharded serving fleet (gol_tpu/fleet/): placement determinism,
manifest round-trips, merged observability, the router over real in-process
workers, spillover routing, and the router-restart replay story.

The load-bearing assertions mirror the serve suite one level up: a job
through the ROUTER ends byte-identical to the oracle, lands on exactly one
worker's journal partition, and survives a router kill+restart without
being lost or double-run — fleet-wide exactly-once is the sum of the
partitions' journals.
"""

import json
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from gol_tpu import oracle
from gol_tpu.config import GameConfig
from gol_tpu.fleet import placement
from gol_tpu.fleet.router import (
    MonotonicCounters, RouterServer, merge_metrics, merge_slo,
    merged_prometheus,
)
from gol_tpu.fleet.workers import Fleet
from gol_tpu.io import text_grid
from gol_tpu.serve import batcher
from gol_tpu.serve.server import GolServer


def _http(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _wait(predicate, timeout=60.0, interval=0.02):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


class TestPlacement:
    def test_quantum_matches_batcher_builtin(self):
        """The router rounds extents with the serve batcher's built-in
        quantum (restated, not imported — the router is jax-free); the two
        constants must never drift."""
        assert placement.PLACEMENT_QUANTUM == batcher.PAD_QUANTUM

    def test_key_rounding_and_label(self):
        k = placement.key_for({"width": 30, "height": 30})
        assert (k.height, k.width) == (32, 32)
        assert k.label() == "32x32/c"
        k = placement.key_for({"width": 33, "height": 65,
                               "convention": "cuda"})
        assert (k.height, k.width) == (96, 64)
        assert k.max_edge == 96
        nosim = placement.key_for({"width": 8, "height": 8,
                                   "check_similarity": False})
        assert "nosim" in nosim.label()
        # Same serve bucket -> same placement key (the affinity contract).
        assert placement.key_for({"width": 30, "height": 30}) == \
            placement.key_for({"width": 32, "height": 29})

    def test_key_rejects_malformed(self):
        with pytest.raises((ValueError, TypeError)):
            placement.key_for({"width": 0, "height": 8})
        with pytest.raises((ValueError, TypeError)):
            placement.key_for({"width": "x", "height": 8})
        with pytest.raises(TypeError):
            placement.key_for({"width": 8, "height": 8,
                               "check_similarity": "false"})
        with pytest.raises(KeyError):
            placement.key_for({"height": 8})

    def test_rank_deterministic_and_spreading(self):
        ids = ["w0", "w1", "w2"]
        labels = [f"{32 * i}x{32 * i}/c" for i in range(1, 21)]
        owners = {placement.rank(lbl, ids)[0] for lbl in labels}
        # Rendezvous hashing must actually spread buckets across workers.
        assert len(owners) >= 2
        for lbl in labels:
            assert placement.rank(lbl, ids) == placement.rank(lbl, ids)
            assert sorted(placement.rank(lbl, ids)) == sorted(ids)

    def test_rank_minimal_disruption(self):
        """Removing one worker must move ONLY that worker's buckets: the
        relative order of the survivors is unchanged for every bucket (the
        compile-budget story — a membership change must not reshuffle hot
        buckets between surviving workers)."""
        ids = ["w0", "w1", "w2", "w3"]
        for i in range(1, 30):
            lbl = f"{32 * i}x{32 * i}/c"
            full = placement.rank(lbl, ids)
            without = placement.rank(lbl, [w for w in ids if w != "w2"])
            assert without == [w for w in full if w != "w2"]


class TestManifest:
    def test_round_trip_and_dead_attached_kept(self, tmp_path):
        fleet = Fleet(str(tmp_path / "fleet"),
                      probe=lambda *a, **k: None)  # nothing is reachable
        fleet.attach("http://127.0.0.1:1/", "wa")
        fleet.attach("http://127.0.0.1:2", "wb", big=True)
        doc = json.loads(open(fleet.manifest_path).read())
        assert {p["id"] for p in doc["partitions"]} == {"wa", "wb"}
        assert all(p["attached"] for p in doc["partitions"])
        big = next(p for p in doc["partitions"] if p["id"] == "wb")
        assert big["big"] is True

        # A fresh fleet (a restarted router) reloads membership; the dead
        # attached workers are kept unhealthy, not dropped — the health
        # loop keeps probing them.
        fleet2 = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        assert fleet2.load() == 2
        assert {w.id for w in fleet2.workers()} == {"wa", "wb"}
        assert all(not w.healthy for w in fleet2.workers())
        assert fleet2.worker("wa").url == "http://127.0.0.1:1"

    def test_load_reattaches_live_workers(self, tmp_path):
        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        fleet.attach("http://127.0.0.1:9", "wa")
        fleet2 = Fleet(str(tmp_path / "fleet"),
                       probe=lambda url, path="/healthz", **k: {"ok": True})
        assert fleet2.load() == 1
        assert fleet2.worker("wa").healthy

    def test_attach_is_idempotent_on_url(self, tmp_path):
        """A restarted `gol fleet` recovers a URL from the manifest AND is
        handed the same --attach flag again: one server must stay ONE
        membership entry (a duplicate would double-count merged metrics
        and double-weight round-robin sharding)."""
        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        a = fleet.attach("http://127.0.0.1:9", "wa")
        again = fleet.attach("http://127.0.0.1:9/")  # trailing-slash form
        assert again is a
        assert len(fleet.workers()) == 1

    def test_slow_boot_worker_is_adopted_by_health_tick(self, tmp_path):
        """A respawn whose boot outlives _await_ready's patience must not
        strand the partition: the health tick keeps parsing the boot
        banner and adopts the URL once it appears."""
        fleet = Fleet(str(tmp_path / "fleet"),
                      probe=lambda url, path="/healthz", **k: {"ok": True})
        log = tmp_path / "w0.log"
        log.write_bytes(b"warming...\n")
        from gol_tpu.fleet.workers import Worker

        w = Worker(id="w0", url=None, journal_dir=str(tmp_path / "w0"),
                   log_path=str(log), log_offset=0,
                   proc=types.SimpleNamespace(poll=lambda: None, pid=1))
        fleet._workers["w0"] = w
        fleet.check_worker(w)
        assert w.url is None  # no banner yet; still waiting, not stranded
        log.write_bytes(b"warming...\nserving on http://127.0.0.1:7777\n")
        fleet.check_worker(w)
        assert w.url == "http://127.0.0.1:7777"
        assert w.healthy

    def test_dead_worker_respawn_does_not_block_the_tick(self, tmp_path):
        """_respawn waits in _await_ready for up to boot_timeout; run
        synchronously inside the health tick that would leave every OTHER
        worker unprobed while one boots — a second concurrent death
        unhandled for minutes. The tick must hand the respawn to a
        background thread and move on, and never start a second respawn
        for the same partition (one journal writer)."""
        import threading

        from gol_tpu.fleet.workers import Worker

        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        started, release = threading.Event(), threading.Event()
        calls = []

        def slow_respawn(worker):
            calls.append(worker.id)
            started.set()
            release.wait(timeout=30)

        fleet._respawn = slow_respawn
        dead = types.SimpleNamespace(poll=lambda: 1, returncode=1, pid=11)
        w = Worker(id="w0", proc=dead, pid=11)
        fleet._workers["w0"] = w
        t0 = time.perf_counter()
        fleet.check_worker(w)
        assert time.perf_counter() - t0 < 1.0  # the tick did not wait
        assert started.wait(timeout=10)
        assert w.respawning
        fleet.check_worker(w)  # next tick: respawn already in flight...
        assert calls == ["w0"]  # ...exactly one respawner
        release.set()
        assert _wait(lambda: not w.respawning, timeout=10)
        # Shutdown joins stragglers so terminate() can't race a boot.
        fleet.stop_health()

    def test_concurrent_manifest_writes_stay_parseable(self, tmp_path):
        """Background respawn threads write the manifest concurrently
        with the health thread; the shared .tmp path must be serialized
        or a half-truncated file can be renamed into place — which a
        restarted router's load() would choke on."""
        import threading

        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        for i in range(4):
            fleet.attach(f"http://127.0.0.1:{9000 + i}", f"w{i}")
        threads = [threading.Thread(target=fleet.write_manifest)
                   for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        doc = json.loads(open(fleet.manifest_path).read())
        assert len(doc["partitions"]) == 4


class TestMerge:
    def test_metrics_merge_sums_and_bounds(self):
        merged = merge_metrics({
            "w0": {"counters": {"jobs_completed_total": 3},
                   "gauges": {"queue_depth": 2},
                   "histograms": {"run_latency_seconds":
                                  {"count": 3, "sum": 1.5, "p50": 0.5,
                                   "p99": 2.0}}},
            "w1": {"counters": {"jobs_completed_total": 4,
                                "jobs_failed_total": 1},
                   "gauges": {"queue_depth": 5},
                   "histograms": {"run_latency_seconds":
                                  {"count": 1, "sum": 9.0, "p50": 1.5,
                                   "p99": 1.0}}},
        })
        assert merged["counters"] == {"jobs_completed_total": 7,
                                      "jobs_failed_total": 1}
        assert merged["gauges"] == {"queue_depth": 7}
        hist = merged["histograms"]["run_latency_seconds"]
        assert hist["count"] == 4 and hist["sum"] == 10.5
        # Quantiles merge as the WORST worker: a conservative upper bound.
        assert hist["p50"] == 1.5 and hist["p99"] == 2.0

    def test_ratio_gauges_merge_by_max_not_sum(self):
        """Intensive gauges (ratios/occupancies, [0,1] per worker) must not
        sum: four workers at 0.9 are NOT at 3.6 of the roofline."""
        merged = merge_metrics({
            "w0": {"gauges": {"dispatch_gap_ratio": 0.9,
                              "ring_slot_occupancy": 0.5,
                              "boards_per_sec": 10.0}},
            "w1": {"gauges": {"dispatch_gap_ratio": 0.4,
                              "ring_slot_occupancy": 0.75,
                              "boards_per_sec": 20.0}},
        })
        assert merged["gauges"]["dispatch_gap_ratio"] == 0.9
        assert merged["gauges"]["ring_slot_occupancy"] == 0.75
        assert merged["gauges"]["boards_per_sec"] == 30.0

    def test_prometheus_text_shape(self):
        merged = merge_metrics({"w0": {"counters": {"jobs_accepted_total": 2},
                                       "gauges": {}, "histograms": {}}})
        text = merged_prometheus(merged, {"workers": 3})
        assert "gol_serve_jobs_accepted_total 2" in text
        assert "gol_fleet_workers 3" in text

    def test_prometheus_router_counters_typed_counter(self):
        """The router's own *_total series must expose as TYPE counter,
        not gauge — Prometheus counter functions (rate/increase) reject
        or misread gauge-typed series."""
        text = merged_prometheus(
            {"counters": {}, "gauges": {}, "histograms": {}},
            {"workers": 3},
            {"jobs_routed_total": 5},
        )
        assert "# TYPE gol_fleet_jobs_routed_total counter" in text
        assert "gol_fleet_jobs_routed_total 5" in text
        assert "# TYPE gol_fleet_workers gauge" in text

    def test_merged_counters_stay_monotonic_across_respawn(self):
        """A respawned worker restarts its counters at zero; the router's
        high-water offsets must keep the fleet-merged counter from
        DECREASING — a non-monotonic 'counter' makes Prometheus
        rate()/increase() report spurious resets exactly during the
        restart windows operators are watching."""
        floors = MonotonicCounters()
        merged = merge_metrics(floors.adjust({
            "w0": {"counters": {"jobs_completed_total": 10}},
            "w1": {"counters": {"jobs_completed_total": 5}},
        }))
        assert merged["counters"]["jobs_completed_total"] == 15
        # w1 respawns: its counter resets to 0...
        merged = merge_metrics(floors.adjust({
            "w0": {"counters": {"jobs_completed_total": 11}},
            "w1": {"counters": {"jobs_completed_total": 0}},
        }))
        assert merged["counters"]["jobs_completed_total"] == 16  # not 11
        # ...and climbs again; the banked pre-respawn total stays in.
        merged = merge_metrics(floors.adjust({
            "w0": {"counters": {"jobs_completed_total": 11}},
            "w1": {"counters": {"jobs_completed_total": 2}},
        }))
        assert merged["counters"]["jobs_completed_total"] == 18

    def test_monotonic_counters_survive_lazily_absent_keys(self):
        """Registries create counters on first inc: a respawned worker's
        snapshot omits a counter entirely until its first event, which
        must read as a reset-to-zero — the banked pre-respawn total stays
        in the merge rather than vanishing with the key."""
        floors = MonotonicCounters()
        merged = merge_metrics(floors.adjust({
            "w0": {"counters": {"jobs_completed_total": 5}},
            "w1": {"counters": {"jobs_completed_total": 10}},
        }))
        assert merged["counters"]["jobs_completed_total"] == 15
        # w1 respawns; its fresh registry has no such counter yet.
        merged = merge_metrics(floors.adjust({
            "w0": {"counters": {"jobs_completed_total": 5}},
            "w1": {"counters": {}},
        }))
        assert merged["counters"]["jobs_completed_total"] == 15  # not 5
        merged = merge_metrics(floors.adjust({
            "w0": {"counters": {"jobs_completed_total": 5}},
            "w1": {"counters": {"jobs_completed_total": 3}},
        }))
        assert merged["counters"]["jobs_completed_total"] == 18

    def test_monotonic_counters_span_the_outage_window(self):
        """While a worker is DEAD it answers no scrape at all — its
        last-known totals must stand in or the merged counter dips for
        the whole outage (caught live: killing a worker halved the
        fleet-merged jobs_completed_total until the respawn finished)."""
        floors = MonotonicCounters()
        merged = merge_metrics(floors.adjust({
            "w0": {"counters": {"jobs_completed_total": 4}},
            "w1": {"counters": {"jobs_completed_total": 4}},
        }))
        assert merged["counters"]["jobs_completed_total"] == 8
        # w0 is down: absent from the scrape entirely.
        merged = merge_metrics(floors.adjust({
            "w1": {"counters": {"jobs_completed_total": 5}},
        }))
        assert merged["counters"]["jobs_completed_total"] == 9  # not 5
        # Back after a respawn (reset) — banked; and after a mere
        # network blip (counters intact) — continued, never double.
        merged = merge_metrics(floors.adjust({
            "w0": {"counters": {"jobs_completed_total": 1}},
            "w1": {"counters": {"jobs_completed_total": 5}},
        }))
        assert merged["counters"]["jobs_completed_total"] == 10

    def test_monotonic_counters_bank_on_known_respawn_overtake(self):
        """A respawned worker can OVERTAKE its old total before the next
        scrape (journal replay plus new load across a long scrape
        interval) — no value regression ever shows, and the old run
        would silently vanish from the merge. The router passes the
        fleet's restart generation so a KNOWN respawn banks at once."""
        floors = MonotonicCounters()
        merged = merge_metrics(floors.adjust(
            {"w1": {"counters": {"jobs_completed_total": 100}}},
            incarnations={"w1": 0},
        ))
        assert merged["counters"]["jobs_completed_total"] == 100
        # Respawned; the fresh run already reads 120 by the next scrape.
        merged = merge_metrics(floors.adjust(
            {"w1": {"counters": {"jobs_completed_total": 120}}},
            incarnations={"w1": 1},
        ))
        assert merged["counters"]["jobs_completed_total"] == 220
        # Steady state afterwards: no double-banking.
        merged = merge_metrics(floors.adjust(
            {"w1": {"counters": {"jobs_completed_total": 125}}},
            incarnations={"w1": 1},
        ))
        assert merged["counters"]["jobs_completed_total"] == 225

    def test_monotonic_histogram_count_and_sum(self):
        """Histogram count/sum are cumulative like counters and expose as
        Prometheus summary _count/_sum series: they must ride the same
        high-water offsets across respawns and outages. Quantiles are
        instantaneous — only live workers contribute them."""
        floors = MonotonicCounters()
        merged = merge_metrics(floors.adjust({
            "w0": {"histograms": {"lat": {"count": 3, "sum": 1.5,
                                          "p50": 0.5}}},
            "w1": {"histograms": {"lat": {"count": 2, "sum": 1.0,
                                          "p50": 0.2}}},
        }))
        h = merged["histograms"]["lat"]
        assert h["count"] == 5 and h["sum"] == 2.5
        # w1 down: its count/sum stand in; its quantile does not.
        merged = merge_metrics(floors.adjust({
            "w0": {"histograms": {"lat": {"count": 3, "sum": 1.5,
                                          "p50": 0.5}}},
        }))
        h = merged["histograms"]["lat"]
        assert h["count"] == 5 and h["sum"] == 2.5
        assert h["p50"] == 0.5
        # Respawned with a fresh (empty) registry: banked, not dropped.
        merged = merge_metrics(floors.adjust({
            "w0": {"histograms": {"lat": {"count": 3, "sum": 1.5}}},
            "w1": {"histograms": {}},
        }))
        h = merged["histograms"]["lat"]
        assert h["count"] == 5 and h["sum"] == 2.5
        merged = merge_metrics(floors.adjust({
            "w0": {"histograms": {"lat": {"count": 3, "sum": 1.5}}},
            "w1": {"histograms": {"lat": {"count": 1, "sum": 0.25}}},
        }))
        h = merged["histograms"]["lat"]
        assert h["count"] == 6 and h["sum"] == 2.75

    def test_slo_merge_worst_wins_and_prefixes(self):
        merged = merge_slo({
            "w0": {"status": "ok", "windows_s": [60, 300],
                   "shed": {"enabled": False, "active": False},
                   "objectives": [{"name": "latency_p99_high",
                                   "status": "ok", "burn": 0.1}]},
            "w1": {"status": "critical", "windows_s": [60, 300],
                   "shed": {"enabled": True, "active": True},
                   "objectives": [{"name": "error_rate",
                                   "status": "critical", "burn": 4.0}]},
            "w2": None,
        })
        assert merged["status"] == "critical"
        assert merged["shed"] == {"enabled": True, "active": True}
        assert {o["name"] for o in merged["objectives"]} == {
            "w0:latency_p99_high", "w1:error_rate"}
        assert merged["unreachable"] == ["w2"]
        assert merged["workers"]["w2"]["status"] == "unreachable"

    def test_slo_merge_unreachable_degrades_headline(self):
        """A fleet serving nothing must never show green: all workers
        unreachable -> critical; some unreachable -> at least warning."""
        ok = {"status": "ok", "windows_s": [60],
              "shed": {"enabled": False, "active": False}, "objectives": []}
        assert merge_slo({"w0": None, "w1": None})["status"] == "critical"
        assert merge_slo({"w0": ok, "w1": None})["status"] == "warning"
        assert merge_slo({"w0": ok, "w1": dict(ok, status="critical")}
                         )["status"] == "critical"


class TestTopFleetRendering:
    def test_per_worker_columns_and_fleet_line(self):
        from gol_tpu.obs import top as obs_top

        metrics = {
            "counters": {"jobs_accepted_total": 4},
            "gauges": {"queue_depth": 1},
            "histograms": {},
            "fleet": {"workers": 2, "healthy": 1, "backpressured": 1,
                      "restarts": 3, "draining": False},
            "workers": {
                "w0": {"health": {"healthy": True, "backpressure": False},
                       "gauges": {"queue_depth": 1, "boards_per_sec": 9.5},
                       "counters": {"jobs_completed_total": 3}},
                "w1": {"unreachable": True, "health": {"healthy": False}},
            },
        }
        slo = {"status": "warning",
               "workers": {"w0": {"status": "ok"},
                           "w1": {"status": "unreachable"}}}
        frame = obs_top.render_frame(metrics, slo, ansi=False)
        assert "fleet: 2 workers, 1 healthy, 1 backpressured" in frame
        assert "w0" in frame and "w1" in frame
        assert "unreachable" in frame
        # A single-server payload renders with no fleet section at all.
        solo = obs_top.render_frame({"counters": {}, "gauges": {},
                                     "histograms": {}}, None, ansi=False)
        assert "fleet:" not in solo and "worker" not in solo


class _Rig(types.SimpleNamespace):
    pass


@pytest.fixture
def rig(tmp_path):
    """Two real in-process workers attached by URL behind a real router —
    the integration surface without subprocess boot costs."""
    workers = {}
    for wid in ("w0", "w1"):
        srv = GolServer(port=0, journal_dir=str(tmp_path / wid),
                        flush_age=0.01)
        srv.start()
        workers[wid] = srv
    fleet = Fleet(str(tmp_path / "fleet"))
    for wid, srv in workers.items():
        fleet.attach(srv.url, wid)
    router = RouterServer(fleet, port=0)
    router.start()
    r = _Rig(router=router, fleet=fleet, workers=workers, tmp=tmp_path)
    yield r
    router.shutdown(cascade=False)
    for srv in workers.values():
        srv.shutdown()


def _submit(base, board, gen_limit=12, **extra):
    status, payload = _http("POST", f"{base}/jobs", {
        "width": board.shape[1], "height": board.shape[0],
        "cells": text_grid.encode(board).decode("ascii"),
        "gen_limit": gen_limit, **extra,
    })
    return status, payload


class TestRouter:
    def test_routed_jobs_bucket_affinity_results_and_timeline(self, rig):
        base = rig.router.url
        boards, ids, owners = {}, {}, {}
        for i in range(8):
            side = 32 if i % 2 == 0 else 30
            board = text_grid.generate(side, side, seed=500 + i)
            status, payload = _submit(base, board)
            assert status == 202, payload
            assert payload["worker"] in rig.workers
            boards[payload["id"]] = board
            ids[payload["id"]] = side
            owners.setdefault(side, set()).add(payload["worker"])
        # Bucket -> worker affinity: every job of one bucket lands on ONE
        # worker (the compiled program stays hot there).
        for side, who in owners.items():
            assert len(who) == 1, owners

        def all_done():
            return all(
                _http("GET", f"{base}/jobs/{j}")[1].get("state") == "done"
                for j in boards
            )
        assert _wait(all_done)
        for job_id, board in boards.items():
            status, result = _http("GET", f"{base}/result/{job_id}")
            assert status == 200
            want = oracle.run(board, GameConfig(gen_limit=12))
            got = text_grid.decode(result["grid"].encode("ascii"),
                                   result["width"], result["height"])
            np.testing.assert_array_equal(np.asarray(got), want.grid)
            assert result["generations"] == want.generations
            # The per-job ops surface forwards too.
            status, tl = _http("GET", f"{base}/jobs/{job_id}/timeline")
            assert status == 200 and tl["segments"]

    def test_merged_observability(self, rig):
        base = rig.router.url
        board = text_grid.generate(32, 32, seed=1)
        status, payload = _submit(base, board)
        assert status == 202
        job_id = payload["id"]
        assert _wait(lambda: _http("GET", f"{base}/jobs/{job_id}")[1]
                     .get("state") == "done")
        status, snap = _http("GET", f"{base}/metrics?format=json")
        assert status == 200
        assert snap["counters"]["jobs_completed_total"] == 1
        assert set(snap["workers"]) == {"w0", "w1"}
        assert snap["fleet"]["workers"] == 2
        assert all("health" in w for w in snap["workers"].values())
        req = urllib.request.Request(f"{base}/metrics")
        with urllib.request.urlopen(req, timeout=10) as resp:
            text = resp.read().decode()
        assert "gol_serve_jobs_completed_total 1" in text
        assert "gol_fleet_workers 2" in text
        status, slo = _http("GET", f"{base}/slo")
        assert status == 200 and slo["status"] in ("ok", "warning",
                                                   "critical")
        names = {o["name"] for o in slo["objectives"]}
        assert any(n.startswith("w0:") for n in names)
        assert any(n.startswith("w1:") for n in names)
        status, fl = _http("GET", f"{base}/fleet")
        assert status == 200
        assert {w["id"] for w in fl["workers"]} == {"w0", "w1"}
        status, hz = _http("GET", f"{base}/healthz")
        assert status == 200 and hz["router"] and hz["fleet"]["workers"] == 2

    def test_unknown_job_and_bad_submit(self, rig):
        base = rig.router.url
        assert _http("GET", f"{base}/jobs/nope")[0] == 404
        assert _http("GET", f"{base}/result/nope")[0] == 404
        assert _http("DELETE", f"{base}/jobs/nope")[0] == 404
        assert _http("POST", f"{base}/jobs", {"width": 8})[0] == 400
        assert _http("POST", f"{base}/jobs",
                     {"width": 0, "height": 8, "cells": ""})[0] == 400
        assert _http("GET", f"{base}/nope")[0] == 404

    def test_drain_cascades_and_refuses_new_work(self, rig):
        base = rig.router.url
        status, payload = _http("POST", f"{base}/drain", {})
        assert status == 200 and payload["drained"], payload
        assert set(payload["workers"]) == {"w0", "w1"}
        for srv in rig.workers.values():
            assert srv.scheduler.draining
        board = text_grid.generate(32, 32, seed=2)
        status, payload = _submit(base, board)
        assert status == 429  # the router's own admission gate


class TestRouterRestart:
    def test_restart_replays_exactly_once(self, tmp_path):
        """The satellite acceptance: kill the router mid-load with workers
        alive, restart it over the same manifest, and prove fleet-wide that
        no accepted job is lost and none is double-run (exactly one done
        record per id across ALL partition journals)."""
        workers = {}
        for wid in ("w0", "w1"):
            srv = GolServer(port=0, journal_dir=str(tmp_path / wid),
                            flush_age=0.01)
            srv.start()
            workers[wid] = srv
        fleet = Fleet(str(tmp_path / "fleet"))
        for wid, srv in workers.items():
            fleet.attach(srv.url, wid)
        router = RouterServer(fleet, port=0)
        router.start()

        boards = {}
        for i in range(12):
            side = 32 if i % 2 == 0 else 30
            board = text_grid.generate(side, side, seed=700 + i)
            # Mixed fates at restart time: half the jobs are long enough
            # to still be in flight when the router dies.
            status, payload = _submit(board=board, base=router.url,
                                      gen_limit=12 if i % 3 else 400)
            assert status == 202, payload
            boards[payload["id"]] = (board, 12 if i % 3 else 400)

        # Kill the router abruptly: NO drain, NO worker shutdown — the
        # workers never notice (they keep computing their queues).
        router.shutdown(cascade=False)

        fleet2 = Fleet(str(tmp_path / "fleet"))
        assert fleet2.load() == 2  # reattached live by URL probe
        router2 = RouterServer(fleet2, port=0)
        router2.start()
        base = router2.url
        try:
            def all_done():
                return all(
                    _http("GET", f"{base}/jobs/{j}")[1].get("state") == "done"
                    for j in boards
                )
            assert _wait(all_done, timeout=120)
            # Results are fetchable through the NEW router (broadcast
            # rebuilds the id->worker map from the workers' own state).
            for job_id, (board, gens) in boards.items():
                status, result = _http("GET", f"{base}/result/{job_id}")
                assert status == 200
                want = oracle.run(board, GameConfig(gen_limit=gens))
                got = text_grid.decode(result["grid"].encode("ascii"),
                                       result["width"], result["height"])
                np.testing.assert_array_equal(np.asarray(got), want.grid)
        finally:
            router2.shutdown(cascade=False)
            for srv in workers.values():
                srv.shutdown()

        # Fleet-wide exactly-once, from the partitioned journals.
        done = {}
        for wid in workers:
            path = tmp_path / wid / "journal.jsonl"
            for line in path.read_bytes().split(b"\n"):
                if not line:
                    continue
                rec = json.loads(line)
                if rec.get("event") == "done":
                    done.setdefault(rec["id"], []).append(wid)
        assert set(done) == set(boards)  # none lost, none invented
        dupes = {k: v for k, v in done.items() if len(v) != 1}
        assert not dupes  # none double-run, fleet-wide


class TestSpilloverAndBigLane:
    def _fake_fleet(self, tmp_path, ids=("wa", "wb"), big=()):
        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        for wid in ids:
            fleet.attach(f"http://{wid}.invalid", wid, big=wid in big)
        return fleet

    def test_shedding_worker_spills_before_clients_see_429(self, tmp_path):
        body = json.dumps({"width": 32, "height": 32}).encode()
        key = placement.key_for(json.loads(body))
        first, second = placement.rank(key.label(), ["wa", "wb"])

        def stub_http(method, url, body=None, raw=None, timeout=0):
            wid = url.split("//")[1].split(".")[0]
            if wid == first:
                return 429, {"error": "shedding load"}
            return 202, {"id": "j1", "state": "queued"}

        fleet = self._fake_fleet(tmp_path)
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, payload = router.route_submit(body)
            assert status == 202
            assert payload["worker"] == second
            # The shedding worker is drained of NEW work from now on...
            assert fleet.worker(first).backpressure
            assert router.registry.counter("route_sheds_total") == 1
            # ...so the next submit of the same bucket goes straight to
            # the spillover worker, first try.
            assert router.candidates(key)[0].id == second
        finally:
            router.httpd.server_close()

    def test_unreachable_worker_spills(self, tmp_path):
        body = json.dumps({"width": 32, "height": 32}).encode()
        key = placement.key_for(json.loads(body))
        first, second = placement.rank(key.label(), ["wa", "wb"])

        def stub_http(method, url, body=None, raw=None, timeout=0):
            wid = url.split("//")[1].split(".")[0]
            if wid == first:
                raise ConnectionRefusedError("down")
            return 202, {"id": "j2", "state": "queued"}

        fleet = self._fake_fleet(tmp_path)
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, payload = router.route_submit(body)
            assert status == 202 and payload["worker"] == second
            assert router.registry.counter("route_errors_total") == 1
        finally:
            router.httpd.server_close()

    def test_ambiguous_submit_failure_does_not_spill(self, tmp_path):
        """A forward that times out AFTER the bytes went out may have been
        accepted (first-dispatch compiles outlive timeouts): spilling
        would run the board twice under two ids. The router must surface
        504 'outcome unknown' instead — only connection-REFUSED (nothing
        delivered) spills."""
        calls = []

        def stub_http(method, url, body=None, raw=None, timeout=0):
            calls.append(url)
            raise TimeoutError("timed out mid-exchange")

        fleet = self._fake_fleet(tmp_path)
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, payload = router.route_submit(
                json.dumps({"width": 32, "height": 32}).encode()
            )
            assert status == 504
            assert "outcome unknown" in payload["error"]
            assert len(calls) == 1  # ONE worker tried; no second delivery
        finally:
            router.httpd.server_close()

    def test_dns_and_unreachable_failures_do_spill(self, tmp_path):
        """DNS failure and host-unreachable guarantee nothing was
        delivered — they must spill like connection-refused, not take the
        ambiguous 504 path (a dead multi-host worker would otherwise
        error out jobs on a fleet with healthy capacity)."""
        import socket as _socket

        body = json.dumps({"width": 32, "height": 32}).encode()
        key = placement.key_for(json.loads(body))
        first, second = placement.rank(key.label(), ["wa", "wb"])

        def stub_http(method, url, body=None, raw=None, timeout=0):
            wid = url.split("//")[1].split(".")[0]
            if wid == first:
                raise urllib.error.URLError(
                    _socket.gaierror(-2, "Name or service not known")
                )
            return 202, {"id": "j3", "state": "queued"}

        fleet = self._fake_fleet(tmp_path)
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, payload = router.route_submit(body)
            assert status == 202 and payload["worker"] == second
        finally:
            router.httpd.server_close()

    def test_all_workers_shedding_propagates_429(self, tmp_path):
        def stub_http(method, url, body=None, raw=None, timeout=0):
            return 429, {"error": "shedding load", "retry_after_s": 5}

        fleet = self._fake_fleet(tmp_path)
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, payload = router.route_submit(
                json.dumps({"width": 32, "height": 32}).encode()
            )
            assert status == 429 and "retry_after_s" in payload
        finally:
            router.httpd.server_close()

    def test_shedding_normals_still_propagate_429_despite_big_lane(
            self, tmp_path):
        """The big lane is the last resort for small jobs ONLY against
        UNREACHABLE normals. Normals shedding 429s means the fleet is
        alive and backpressuring on purpose — the client must see the
        429 + Retry-After, not have its overflow silently compiled onto
        the mesh-sharded lane's reserved budget."""
        def stub_http(method, url, body=None, raw=None, timeout=0):
            if "big0" in url:
                return 202, {"id": "jb", "state": "queued"}
            return 429, {"error": "shedding load", "retry_after_s": 5}

        fleet = self._fake_fleet(tmp_path, ids=("wa", "wb", "big0"),
                                 big=("big0",))
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, payload = router.route_submit(
                json.dumps({"width": 32, "height": 32}).encode()
            )
            assert status == 429 and "retry_after_s" in payload
        finally:
            router.httpd.server_close()

    def test_big_lane_429_does_not_block_other_bigs(self, tmp_path):
        """A 429 from a BIG worker is that worker being full, not the
        small-lane backpressure signal: in a bigs-only fleet (bigs ARE
        the routing pool) the next big still gets its try — a client
        must only see 429 when every routable worker shed."""
        def stub_http(method, url, body=None, raw=None, timeout=0):
            if "biga" in url:
                return 429, {"error": "shedding load", "retry_after_s": 5}
            return 202, {"id": "jb", "state": "queued"}

        fleet = self._fake_fleet(tmp_path, ids=("biga", "bigb"),
                                 big=("biga", "bigb"))
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, payload = router.route_submit(
                json.dumps({"width": 32, "height": 32}).encode()
            )
            assert status == 202 and payload["worker"] == "bigb"
        finally:
            router.httpd.server_close()

    def test_unreachable_normals_walk_the_whole_big_tail(self, tmp_path):
        """With every normal unreachable, a shedding FIRST big must not
        end the tail walk: the next big takes the job."""
        def stub_http(method, url, body=None, raw=None, timeout=0):
            if "biga" in url:
                return 429, {"error": "shedding load", "retry_after_s": 5}
            if "bigb" in url:
                return 202, {"id": "jb", "state": "queued"}
            raise ConnectionRefusedError("down")

        fleet = self._fake_fleet(tmp_path, ids=("wa", "biga", "bigb"),
                                 big=("biga", "bigb"))
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, payload = router.route_submit(
                json.dumps({"width": 32, "height": 32}).encode()
            )
            assert status == 202 and payload["worker"] == "bigb"
        finally:
            router.httpd.server_close()

    def test_mixed_shed_and_unreachable_normals_propagate_429(
            self, tmp_path):
        """One normal shedding + one unreachable: a live shed signal
        anywhere still wins over big-lane spillover."""
        def stub_http(method, url, body=None, raw=None, timeout=0):
            if "big0" in url:
                return 202, {"id": "jb", "state": "queued"}
            if "wa" in url:
                raise ConnectionRefusedError("down")
            return 429, {"error": "shedding load", "retry_after_s": 5}

        fleet = self._fake_fleet(tmp_path, ids=("wa", "wb", "big0"),
                                 big=("big0",))
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, payload = router.route_submit(
                json.dumps({"width": 32, "height": 32}).encode()
            )
            assert status == 429 and "retry_after_s" in payload
        finally:
            router.httpd.server_close()

    def test_oversized_boards_route_to_big_lane(self, tmp_path):
        fleet = self._fake_fleet(tmp_path, ids=("wa", "wb", "big0"),
                                 big=("big0",))
        router = RouterServer(fleet, port=0, big_edge=1024)
        try:
            big_key = placement.key_for({"width": 2048, "height": 64})
            order = router.candidates(big_key)
            assert order[0].id == "big0"  # the dedicated lane owns it
            assert {w.id for w in order} == {"wa", "wb", "big0"}  # spillover
            small_key = placement.key_for({"width": 64, "height": 64})
            assert all(not w.big for w in router.candidates(small_key)[:2])
        finally:
            router.httpd.server_close()

    def test_job_map_evicts_on_terminal_fetch_and_caps(self, tmp_path):
        """The router's id->worker map is memory-only and must stay
        bounded: fetching a result (or tombstone) evicts the entry, and
        the FIFO cap is the backstop for never-collected jobs."""
        counter = {"n": 0}

        def stub_http(method, url, body=None, raw=None, timeout=0):
            if method == "POST" and url.endswith("/jobs"):
                counter["n"] += 1
                return 202, {"id": f"j{counter['n']}", "state": "queued"}
            if "/result/" in url:
                return 200, {"id": url.rsplit("/", 1)[1], "grid": ""}
            return 404, {}

        fleet = self._fake_fleet(tmp_path, ids=("wa",))
        router = RouterServer(fleet, port=0, http=stub_http)
        router._jobs_cap = 4
        try:
            body = json.dumps({"width": 32, "height": 32}).encode()
            status, payload = router.route_submit(body)
            assert status == 202 and payload["id"] in router._jobs
            status, _ = router.forward_job("GET", payload["id"], "result")
            assert status == 200
            assert payload["id"] not in router._jobs  # evicted on fetch
            for _ in range(8):
                router.route_submit(body)
            assert len(router._jobs) == 4  # FIFO cap holds
        finally:
            router.httpd.server_close()

    def test_small_jobs_spill_to_big_lane_as_true_last_resort(self, tmp_path):
        """A fleet whose normal workers are ALL unreachable must not 503
        small jobs while a healthy big-lane worker sits idle — workers
        re-bucket jobs themselves, so spillover there is correctness-safe.
        But the big lane stays LAST in the order: small jobs only reach it
        when every normal worker (even unhealthy ones) already failed."""
        fleet = self._fake_fleet(tmp_path, ids=("wa", "wb", "big0"),
                                 big=("big0",))
        router = RouterServer(fleet, port=0, big_edge=1024)
        try:
            small_key = placement.key_for({"width": 64, "height": 64})
            order = router.candidates(small_key)
            assert [w.id for w in order[:2]] != ["big0"]  # normals first
            assert order[-1].id == "big0"
            for wid in ("wa", "wb"):
                fleet.worker(wid).healthy = False
            assert router.candidates(small_key)[-1].id == "big0"
            # An unhealthy big lane is no resort at all.
            fleet.worker("big0").healthy = False
            assert all(w.id != "big0"
                       for w in router.candidates(small_key))
        finally:
            router.httpd.server_close()

    def test_route_submit_lands_on_big_lane_when_normals_unreachable(
            self, tmp_path):
        def stub_http(method, url, body=None, raw=None, timeout=0):
            if "big0" in url:
                return 202, {"id": "jb", "state": "queued"}
            raise ConnectionRefusedError("down")

        fleet = self._fake_fleet(tmp_path, ids=("wa", "wb", "big0"),
                                 big=("big0",))
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, payload = router.route_submit(
                json.dumps({"width": 32, "height": 32}).encode()
            )
            assert status == 202 and payload["worker"] == "big0"
        finally:
            router.httpd.server_close()

    def test_concurrent_scrapes_single_flight(self, tmp_path):
        """Concurrent /metrics scrapes must neither overlap (out-of-order
        snapshots would double-bank a respawn in MonotonicCounters) nor
        queue full fan-outs behind each other (a dead worker's connect
        timeout per queued scrape re-freezes `gol top` mid-outage): a
        late arrival shares the in-flight scrape's result."""
        import threading

        calls = []
        gate = threading.Event()

        def stub_http(method, url, body=None, raw=None, timeout=0):
            calls.append(url)
            gate.wait(timeout=10)
            return 200, {"counters": {"jobs_completed_total": 1},
                         "gauges": {}, "histograms": {}}

        fleet = self._fake_fleet(tmp_path, ids=("wa",))
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            results = []
            threads = [
                threading.Thread(
                    target=lambda: results.append(router.metrics_json())
                )
                for _ in range(3)
            ]
            for t in threads:
                t.start()
            time.sleep(0.3)  # one scrape in flight, the others waiting
            gate.set()
            for t in threads:
                t.join(timeout=15)
            assert len(results) == 3
            assert len(calls) == 1  # ONE fan-out served all three
            for r in results:
                assert r["counters"]["jobs_completed_total"] == 1
        finally:
            router.httpd.server_close()

    def test_unhealthy_workers_sink_to_the_tail(self, tmp_path):
        fleet = self._fake_fleet(tmp_path, ids=("wa", "wb"))
        key = placement.key_for({"width": 32, "height": 32})
        first = placement.rank(key.label(), ["wa", "wb"])[0]
        fleet.worker(first).healthy = False
        router = RouterServer(fleet, port=0)
        try:
            order = router.candidates(key)
            assert order[0].id != first and order[-1].id == first
        finally:
            router.httpd.server_close()


class TestShardAcross:
    def test_submit_shard_across_fleet_round_robin(self, rig, tmp_path,
                                                   capsys):
        """`gol submit --shard-across` reads GET /fleet and fans boards
        directly over the workers round-robin; results come back whole."""
        from gol_tpu import cli

        inputs = []
        for i in range(4):
            board = text_grid.generate(32, 32, seed=900 + i)
            path = tmp_path / f"in{i}.txt"
            path.write_bytes(text_grid.encode(board))
            inputs.append(str(path))
        rc = cli.main([
            "submit", "32", "32", *inputs,
            "--server", rig.router.url, "--shard-across",
            "--gen-limit", "8", "--output-dir", str(tmp_path / "out"),
        ])
        assert rc == 0
        out = capsys.readouterr()
        assert "sharding 4 board(s) across 2 fleet worker(s)" in out.err
        for i in range(4):
            assert (tmp_path / "out" / f"in{i}.txt.out").exists()
        # Round-robin put jobs on BOTH workers directly.
        for srv in rig.workers.values():
            assert srv.metrics.counter("jobs_accepted_total") == 2

    def test_collect_results_survives_one_dead_target(self, tmp_path,
                                                      capsys):
        """One dead sharded target (a worker respawned on a new port)
        abandons only ITS jobs after the timeout; jobs on the live target
        still complete — previously the first unreachable target aborted
        the whole collection."""
        import argparse

        from gol_tpu import cli

        srv = GolServer(port=0, journal_dir=str(tmp_path / "j"),
                        flush_age=0.01)
        srv.start()
        try:
            board = text_grid.generate(32, 32, seed=42)
            status, payload = _submit(srv.url, board, gen_limit=8)
            assert status == 202
            path = tmp_path / "live.txt"
            path.write_bytes(text_grid.encode(board))
            pending = {
                payload["id"]: (str(path), srv.url),
                "deadjob": ("dead.txt", "http://127.0.0.1:1"),
            }
            outdir = tmp_path / "out"
            outdir.mkdir()
            args = argparse.Namespace(poll_interval=0.05, server_timeout=0.5)
            rc = cli._collect_results(pending, args, str(outdir))
            assert rc == 1  # the dead target's job was abandoned...
            out = capsys.readouterr()
            assert "giving up on 1 job(s) there" in out.err
            # ...but the live worker's result landed regardless.
            assert (outdir / "live.txt.out").exists()
        finally:
            srv.shutdown()

    def test_collect_results_dead_target_holding_two_jobs(self, tmp_path,
                                                          capsys):
        """A dead sharded target holding TWO pending jobs: target_down()
        deletes every job on that base, and the sweep's stale snapshot
        then revisits the second one — the lookup must tolerate the
        mid-sweep eviction (previously a KeyError crashed the whole
        client, losing collection on healthy targets too)."""
        import argparse

        from gol_tpu import cli

        srv = GolServer(port=0, journal_dir=str(tmp_path / "j"),
                        flush_age=0.01)
        srv.start()
        try:
            board = text_grid.generate(32, 32, seed=43)
            status, payload = _submit(srv.url, board, gen_limit=8)
            assert status == 202
            path = tmp_path / "live.txt"
            path.write_bytes(text_grid.encode(board))
            # The dead jobs FIRST: the first one's timeout evicts both,
            # and the snapshot still holds the second.
            pending = {
                "deadjob1": ("dead1.txt", "http://127.0.0.1:1"),
                "deadjob2": ("dead2.txt", "http://127.0.0.1:1"),
                payload["id"]: (str(path), srv.url),
            }
            outdir = tmp_path / "out"
            outdir.mkdir()
            args = argparse.Namespace(poll_interval=0.05, server_timeout=0.5)
            rc = cli._collect_results(pending, args, str(outdir))
            assert rc == 1
            assert "giving up on 2 job(s) there" in capsys.readouterr().err
            assert (outdir / "live.txt.out").exists()
        finally:
            srv.shutdown()

    def test_submit_shard_across_single_server_is_noop(self, tmp_path,
                                                       capsys):
        from gol_tpu import cli

        srv = GolServer(port=0, journal_dir=str(tmp_path / "j"),
                        flush_age=0.01)
        srv.start()
        try:
            board = text_grid.generate(32, 32, seed=77)
            path = tmp_path / "in.txt"
            path.write_bytes(text_grid.encode(board))
            rc = cli.main([
                "submit", "32", "32", str(path),
                "--server", srv.url, "--shard-across", "--gen-limit", "8",
            ])
            assert rc == 0
            assert "sharding" not in capsys.readouterr().err
            assert srv.metrics.counter("jobs_accepted_total") == 1
        finally:
            srv.shutdown()
