"""Text-grid codec tests: the byte-level format contract (README.md:61-63)."""

import numpy as np
import pytest

from gol_tpu.io import text_grid


def test_encode_layout():
    g = np.array([[1, 0, 1], [0, 1, 0]], dtype=np.uint8)
    assert text_grid.encode(g) == b"101\n010\n"


def test_roundtrip_random():
    g = text_grid.generate(37, 23, seed=0)
    assert g.shape == (23, 37)
    data = text_grid.encode(g)
    assert len(data) == 23 * (37 + 1)
    back = text_grid.decode(data, 37, 23)
    assert np.array_equal(back, g)


def test_output_is_valid_input():
    # The final output file is a valid input file (src/game.c:25-40 emits what
    # src/game.c:154-165 parses) — the manual-resume property.
    g = text_grid.generate(16, 16, seed=1)
    assert np.array_equal(text_grid.decode(text_grid.encode(g), 16, 16), g)


def test_decode_tolerates_missing_trailing_newline():
    # Reference's fgetc parser doesn't require the final newline.
    assert np.array_equal(
        text_grid.decode(b"10\n01", 2, 2), np.array([[1, 0], [0, 1]], np.uint8)
    )


def test_decode_skips_interior_newlines_only():
    # Any non-'\n' byte is a cell; only '1' is alive (src/game.c:158-164,83).
    g = text_grid.decode(b"1x\n0 \n", 2, 2)
    assert np.array_equal(g, np.array([[1, 0], [0, 0]], np.uint8))


def test_decode_too_short_raises():
    with pytest.raises(ValueError):
        text_grid.decode(b"10\n", 2, 2)


def test_file_roundtrip(tmp_path):
    g = text_grid.generate(30, 30, seed=2)
    p = tmp_path / "grid.out"
    text_grid.write_grid(str(p), g)
    assert p.read_bytes() == text_grid.encode(g)
    assert np.array_equal(text_grid.read_grid(str(p), 30, 30), g)


def test_generate_density_extremes():
    assert text_grid.generate(8, 8, density=0.0, seed=0).sum() == 0
    assert text_grid.generate(8, 8, density=1.0, seed=0).sum() == 64


def test_generate_deterministic_with_seed():
    a = text_grid.generate(12, 12, seed=42)
    b = text_grid.generate(12, 12, seed=42)
    assert np.array_equal(a, b)


def test_generate_to_file_matches_whole_array_route(tmp_path):
    """Streamed generation writes byte-identical files to the in-memory
    route for the same seed (the RNG stream is consumed in the same order)."""
    whole = tmp_path / "whole.txt"
    streamed = tmp_path / "streamed.txt"
    text_grid.write_grid(str(whole), text_grid.generate(96, 40, seed=7))
    text_grid.generate_to_file(str(streamed), 96, 40, seed=7, chunk_rows=16)
    assert whole.read_bytes() == streamed.read_bytes()
