"""Fleet-wide distributed tracing + durable metrics history (ISSUE 10).

The load-bearing assertions:

- **propagation compatibility** — with tracing DISABLED (the default), an
  ``X-Gol-Trace`` header on a submit changes NOTHING (response shape, job
  state, span ring all byte-identical to a headerless submit), and a
  tracing router never adds the header; enabled, the worker adopts the
  propagated id and its flow events chain onto the router's;
- **stitching** — ``gol fleet-trace`` merges per-process ``/debug/trace``
  payloads into one Chrome document with per-process pid lanes and the
  per-process clock-skew adjustment applied (pinned on injected skew);
- **history** — the snapshot ring rotates, compacts to its byte cap,
  tolerates torn tails, continues numbering across respawns; the
  router-side history (fed through the PR-8 MonotonicCounters floors)
  stays monotonic through a worker reset; ``tools/bench_diff.py
  --history`` exits nonzero on a regressed window;
- **spillover/429/504 walks** keep their PR-8 status codes exactly, with
  or without tracing.
"""

import json
import os
import urllib.error
import urllib.request

import pytest

from gol_tpu.fleet import placement
from gol_tpu.fleet.router import RouterServer
from gol_tpu.fleet.workers import Fleet
from gol_tpu.io import text_grid
from gol_tpu.obs import (
    fleettrace, history, propagate, report, sampler as obs_sampler, trace,
)
from gol_tpu.serve.server import GolServer

import tools.bench_diff as bench_diff


@pytest.fixture(autouse=True)
def _clean_tracer():
    """Every test starts and ends with the tracer off, empty, and at the
    default ring size (the test_obs.py hygiene rule)."""
    trace.enable(ring_size=trace._DEFAULT_RING)
    trace.disable()
    trace.clear()
    yield
    trace.enable(ring_size=trace._DEFAULT_RING)
    trace.disable()
    trace.clear()


def _http(method, url, body=None, headers=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    hdrs = {"Content-Type": "application/json"} if body else {}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=data, method=method, headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestPropagate:
    def test_round_trip(self):
        tid = propagate.new_trace_id()
        value = propagate.encode(tid, propagate.sender_label())
        assert propagate.decode(value) == (tid, propagate.sender_label())
        assert propagate.decode(propagate.encode(tid)) == (tid, None)

    def test_malformed_values_degrade_to_none(self):
        for bad in (None, "", 7, "not a token!", "a/b/c!", "x" * 65,
                    "ok/" + "y" * 65, "sp ace"):
            assert propagate.decode(bad) is None

    def test_encode_rejects_bad_tokens(self):
        with pytest.raises(ValueError):
            propagate.encode("bad token!")
        with pytest.raises(ValueError):
            propagate.encode("ok", "bad parent!")


class TestWorkerAdoption:
    """The serve-side half of the propagation contract, over real HTTP."""

    def _boot(self, tmp_path):
        srv = GolServer(port=0, journal_dir=str(tmp_path / "j"),
                        flush_age=0.01, sample_interval=0)
        srv.start()
        return srv

    def _submit(self, srv, headers=None, seed=1):
        board = text_grid.generate(16, 16, seed=seed)
        return _http("POST", f"{srv.url}/jobs", {
            "width": 16, "height": 16,
            "cells": text_grid.encode(board).decode("ascii"),
            "gen_limit": 2,
        }, headers=headers)

    def test_header_ignored_while_tracing_disabled(self, tmp_path):
        """Old-worker behavior, byte-identical: a headered submit against
        a tracing-disabled server is indistinguishable from a headerless
        one — same response shape, no adopted trace, empty span ring."""
        srv = self._boot(tmp_path)
        try:
            hdr = {propagate.TRACE_HEADER: propagate.encode("cafe1234")}
            status_h, payload_h = self._submit(srv, headers=hdr, seed=1)
            status_n, payload_n = self._submit(srv, headers=None, seed=2)
            assert status_h == status_n == 202
            assert set(payload_h) == set(payload_n) == {"id", "state"}
            assert payload_h["state"] == payload_n["state"]
            for payload in (payload_h, payload_n):
                job = srv.scheduler.job(payload["id"])
                assert job.trace is None
                assert job.flow_id() == job.id
            assert trace.snapshot() == []  # nothing recorded, ever
        finally:
            srv.shutdown()

    def test_no_header_submit_is_byte_identical_with_tracing_on(self, tmp_path):
        """Old-client-to-new-server: without the header, a traced server's
        flow events are EXACTLY the PR-7 shape — phase "s" under the job's
        own id."""
        srv = self._boot(tmp_path)
        try:
            trace.enable()
            status, payload = self._submit(srv)
            assert status == 202
            flows = [s for s in trace.snapshot()
                     if (s["attrs"] or {}).get("flow_phase")]
            starts = [s for s in flows
                      if s["attrs"]["flow_phase"] == "s"]
            assert starts and starts[0]["attrs"]["flow_id"] == payload["id"]
        finally:
            srv.shutdown()

    def test_traced_server_adopts_header(self, tmp_path):
        srv = self._boot(tmp_path)
        try:
            trace.enable()
            tid = "feed0123deadbeef"
            hdr = {propagate.TRACE_HEADER: propagate.encode(tid, "router-1")}
            status, payload = self._submit(srv, headers=hdr)
            assert status == 202
            job = srv.scheduler.job(payload["id"])
            assert job.trace == tid and job.flow_id() == tid
            flows = [s for s in trace.snapshot()
                     if (s["attrs"] or {}).get("flow_id") == tid]
            # The adopting side STEPS the router's flow (phase "t"), never
            # opens a second chain with "s".
            assert flows and flows[0]["attrs"]["flow_phase"] == "t"
            assert not any(s["attrs"]["flow_phase"] == "s" for s in flows)
        finally:
            srv.shutdown()

    def test_malformed_header_degrades_to_own_id(self, tmp_path):
        srv = self._boot(tmp_path)
        try:
            trace.enable()
            hdr = {propagate.TRACE_HEADER: "not a token!!/nope"}
            status, payload = self._submit(srv, headers=hdr)
            assert status == 202
            assert srv.scheduler.job(payload["id"]).trace is None
        finally:
            srv.shutdown()


class TestRouterPropagation:
    def _fake_fleet(self, tmp_path, ids=("wa", "wb")):
        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        for wid in ids:
            fleet.attach(f"http://{wid}.invalid", wid)
        return fleet

    BODY = json.dumps({"width": 32, "height": 32}).encode()

    def test_disabled_router_sends_no_header(self, tmp_path):
        """The disabled path is the PR-8 wire format exactly: the stub
        accepts NO headers kwarg, so any stamped header would raise."""
        def stub_http(method, url, body=None, raw=None, timeout=0):
            return 202, {"id": "j1", "state": "queued"}

        router = RouterServer(self._fake_fleet(tmp_path), port=0,
                              http=stub_http)
        try:
            status, payload = router.route_submit(self.BODY)
            assert status == 202
            assert trace.snapshot() == []
        finally:
            router.httpd.server_close()

    def test_traced_router_stamps_header_and_flow(self, tmp_path):
        seen = {}

        def stub_http(method, url, body=None, raw=None, timeout=0,
                      headers=None):
            seen["headers"] = headers
            return 202, {"id": "j1", "state": "queued"}

        router = RouterServer(self._fake_fleet(tmp_path), port=0,
                              http=stub_http)
        try:
            trace.enable()
            status, _ = router.route_submit(self.BODY)
            assert status == 202
            ctx = propagate.decode(
                (seen["headers"] or {}).get(propagate.TRACE_HEADER)
            )
            assert ctx is not None
            tid, parent = ctx
            assert parent == propagate.sender_label()
            spans = trace.snapshot()
            flows = [s for s in spans
                     if (s["attrs"] or {}).get("flow_id") == tid]
            assert flows and flows[0]["attrs"]["flow_phase"] == "s"
            names = [s["name"] for s in spans]
            assert "fleet.submit" in names and "fleet.forward" in names
            submit = next(s for s in spans if s["name"] == "fleet.submit")
            # The candidate ranking rides the span (the walk's evidence).
            assert set(submit["attrs"]["candidates"].split(",")) == {
                "wa", "wb"
            }
        finally:
            router.httpd.server_close()

    def test_traced_spillover_walk_keeps_status_codes(self, tmp_path):
        """429-then-202, unreachable-then-202, and the ambiguous 504 all
        answer EXACTLY their PR-8 statuses with tracing on — spans and
        spill events are evidence, never behavior."""
        key = placement.key_for(json.loads(self.BODY))
        first, second = placement.rank(key.label(), ["wa", "wb"])

        def shed_then_accept(method, url, body=None, raw=None, timeout=0,
                             headers=None):
            wid = url.split("//")[1].split(".")[0]
            if wid == first:
                return 429, {"error": "shedding"}
            return 202, {"id": "j1", "state": "queued"}

        trace.enable()
        router = RouterServer(self._fake_fleet(tmp_path), port=0,
                              http=shed_then_accept)
        try:
            status, payload = router.route_submit(self.BODY)
            assert status == 202 and payload["worker"] == second
            spills = [s for s in trace.snapshot()
                      if s["name"] == "fleet.spill"]
            assert spills and spills[0]["attrs"]["reason"] == "shed"
        finally:
            router.httpd.server_close()

        trace.clear()

        def ambiguous(method, url, body=None, raw=None, timeout=0,
                      headers=None):
            raise TimeoutError("mid-exchange")

        router = RouterServer(self._fake_fleet(tmp_path, ids=("wc", "wd")),
                              port=0, http=ambiguous)
        try:
            status, payload = router.route_submit(self.BODY)
            assert status == 504 and "outcome unknown" in payload["error"]
            assert any(s["name"] == "fleet.ambiguous"
                       for s in trace.snapshot())
        finally:
            router.httpd.server_close()


class TestStitch:
    @staticmethod
    def _payload(pid, anchor_ns, spans, anchor_perf=100.0):
        return {
            "enabled": True,
            "meta": {"pid": pid, "anchor_perf_s": anchor_perf,
                     "anchor_unix_ns": anchor_ns, "dropped_spans": 0},
            "spans": spans,
        }

    @staticmethod
    def _span(name, start, **attrs):
        return {"name": name, "start_s": start, "duration_s": 0.01,
                "tid": 7, "thread_name": "t", "depth": 0,
                "attrs": attrs or None}

    def test_skew_adjustment_is_applied(self):
        """Two processes whose wall anchors differ by exactly 500us: the
        later process's events shift by +500us on the stitched axis —
        the injected-skew pin of the acceptance criteria."""
        router = self._payload(10, 1_000_000_000, [
            self._span("fleet.submit", 100.5),
            self._span("job", 100.5, flow_phase="s", flow_id="abc"),
        ])
        worker = self._payload(20, 1_000_500_000, [
            self._span("serve.batch", 100.2),
            self._span("job", 100.2, flow_phase="t", flow_id="abc",
                       state="claimed"),
        ])
        doc = fleettrace.stitch([
            {"name": "router", "payload": router},
            {"name": "w0", "payload": worker},
        ])
        ts = {(e["pid"], e["name"]): e["ts"]
              for e in doc["traceEvents"] if e["ph"] != "M"}
        # Router: (100.5 - 100.0) * 1e6 + 0 skew; worker: 0.2s + 500us.
        assert ts[(10, "fleet.submit")] == pytest.approx(500_000.0)
        assert ts[(20, "serve.batch")] == pytest.approx(200_500.0)
        procs = doc["otherData"]["processes"]
        assert procs["router"]["skew_us_vs_origin"] == 0.0
        assert procs["w0"]["skew_us_vs_origin"] == pytest.approx(500.0)
        # Both processes present with their own pids + name metadata.
        assert {e["pid"] for e in doc["traceEvents"]} == {10, 20}
        metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert {m["args"]["name"] for m in metas} == {
            "router (pid 10)", "w0 (pid 20)"
        }
        # The flow chain crosses processes under ONE id.
        flows = [e for e in doc["traceEvents"] if e["ph"] in ("s", "t")]
        assert {f["id"] for f in flows} == {"abc"}
        assert {f["pid"] for f in flows} == {10, 20}

    def test_pid_collision_gets_synthetic_lanes(self):
        """In-process test fleets report one pid for every lane; the
        stitcher must keep the lanes distinct (and record the real pid)."""
        a = self._payload(42, 1_000_000_000, [self._span("x", 100.1)])
        b = self._payload(42, 1_000_000_000, [self._span("y", 100.1)])
        doc = fleettrace.stitch([
            {"name": "router", "payload": a},
            {"name": "w0", "payload": b},
        ])
        procs = doc["otherData"]["processes"]
        assert procs["router"]["pid"] != procs["w0"]["pid"]
        assert procs["router"]["real_pid"] == procs["w0"]["real_pid"] == 42

    def test_pid_in_synthetic_block_cannot_hang_the_probe(self):
        """A real pid that IS its own synthetic fallback (1_00X_000 +
        pid%1000 — reachable on hosts with a large pid_max) used to make
        the collision loop a fixed point and spin forever; the probe must
        advance and terminate with distinct lanes."""
        # index 1's fallback for real pid 1001234 is 1_000_000 + 1000 +
        # 234 = 1001234 — the colliding pid itself.
        a = self._payload(1_001_234, 1_000_000_000, [self._span("x", 100.1)])
        b = self._payload(1_001_234, 1_000_000_000, [self._span("y", 100.1)])
        doc = fleettrace.stitch([
            {"name": "router", "payload": a},
            {"name": "w0", "payload": b},
        ])
        procs = doc["otherData"]["processes"]
        pids = {procs["router"]["pid"], procs["w0"]["pid"]}
        assert len(pids) == 2

    def test_unreachable_and_disabled_processes_are_skipped(self):
        live = self._payload(10, 1_000_000_000, [self._span("x", 100.1)])
        disabled = {"enabled": False,
                    "meta": {"pid": 11, "anchor_perf_s": 0.0,
                             "anchor_unix_ns": 0},
                    "spans": []}
        doc = fleettrace.stitch([
            {"name": "router", "payload": live},
            {"name": "w0", "payload": None, "error": "unreachable"},
            {"name": "w1", "payload": disabled},
        ])
        assert set(doc["otherData"]["processes"]) == {"router"}
        skipped = {s["name"]: s["reason"]
                   for s in doc["otherData"]["skipped"]}
        assert skipped["w0"] == "unreachable"
        assert "disabled" in skipped["w1"]

    def test_report_renders_per_process_tables_and_fleet_gap(self, tmp_path):
        """A stitched file renders one phase table per process plus the
        router-forward -> worker-claim fleet-queueing gap."""
        router = self._payload(10, 1_000_000_000, [
            self._span("fleet.submit", 100.5),
            self._span("job", 100.5, flow_phase="s", flow_id="abc"),
        ])
        worker = self._payload(20, 1_000_000_000, [
            self._span("serve.batch", 100.9),
            self._span("job", 100.52, flow_phase="t", flow_id="abc"),
            self._span("job", 100.9, flow_phase="t", flow_id="abc",
                       state="claimed"),
            self._span("job", 100.95, flow_phase="f", flow_id="abc"),
        ])
        doc = fleettrace.stitch([
            {"name": "router", "payload": router},
            {"name": "w0", "payload": worker},
        ])
        path = tmp_path / "fleet-trace.json"
        path.write_text(json.dumps(doc))
        text = report.render(str(path))
        assert "process 10 (router)" in text
        assert "process 20 (w0)" in text
        assert "fleet_queueing" in text
        # The gap prefers the CLAIMED step: 100.9 - 100.5 = 400ms.
        assert "p50 400.000 ms" in text

    def test_collect_against_live_fleet(self, tmp_path):
        """collect() walks GET /fleet and /debug/trace over real HTTP; a
        stitched export from in-process workers still yields distinct
        lanes (synthetic pids) and the cross-process flow chain."""
        workers = {}
        for wid in ("w0", "w1"):
            srv = GolServer(port=0, journal_dir=str(tmp_path / wid),
                            flush_age=0.01, sample_interval=0)
            srv.start()
            workers[wid] = srv
        fleet = Fleet(str(tmp_path / "fleet"))
        for wid, srv in workers.items():
            fleet.attach(srv.url, wid)
        router = RouterServer(fleet, port=0)
        router.start()
        try:
            trace.enable()
            board = text_grid.generate(16, 16, seed=9)
            status, payload = _http("POST", f"{router.url}/jobs", {
                "width": 16, "height": 16,
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": 2,
            })
            assert status == 202

            def done():
                s, p = _http("GET", f"{router.url}/jobs/{payload['id']}")
                return s == 200 and p.get("state") == "done"
            deadline = 60
            import time as _time
            while not done() and deadline > 0:
                _time.sleep(0.05)
                deadline -= 0.05
            entries = fleettrace.collect(router.url)
            assert {e["name"] for e in entries} == {"router", "w0", "w1"}
            assert all(e["payload"] is not None for e in entries)
            out = tmp_path / "stitched.json"
            doc = fleettrace.export(router.url, str(out))
            with open(out) as f:
                json.load(f)  # valid JSON on disk
            # One flow id appears in BOTH the router lane and a worker
            # lane: the cross-process chain.
            flows = [e for e in doc["traceEvents"]
                     if e.get("ph") in ("s", "t", "f")]
            by_id = {}
            for e in flows:
                by_id.setdefault(e["id"], set()).add(e["pid"])
            assert any(len(pids) > 1 for pids in by_id.values()), by_id
        finally:
            router.shutdown(cascade=False)
            for srv in workers.values():
                srv.shutdown()


class TestHistory:
    @staticmethod
    def _writer(d, **kw):
        t = [0.0]

        def clock():
            t[0] += 1.0
            return t[0]
        kw.setdefault("clock", clock)
        return history.HistoryWriter(str(d), **kw)

    def test_round_trip_and_rotation(self, tmp_path):
        w = self._writer(tmp_path / "h", segment_bytes=500,
                         total_bytes=10_000)
        for i in range(20):
            w.append({"counters": {"jobs_completed_total": i},
                      "gauges": {"queue_depth": i % 3}})
        w.close()
        segs = [n for n in os.listdir(tmp_path / "h")
                if n.startswith("seg-")]
        assert len(segs) > 1  # rotated
        rs = history.runs(str(tmp_path / "h"))
        assert len(rs) == 1  # one incarnation = one run across segments
        samples = rs[0]["samples"]
        assert [s["counters"]["jobs_completed_total"] for s in samples] == \
            list(range(20))
        assert [s["seq"] for s in samples] == list(range(1, 21))

    def test_compaction_respects_byte_cap(self, tmp_path):
        w = self._writer(tmp_path / "h", segment_bytes=400,
                         total_bytes=1200)
        for i in range(200):
            w.append({"counters": {"c": i}})
        w.close()
        d = str(tmp_path / "h")
        total = sum(os.path.getsize(os.path.join(d, n))
                    for n in os.listdir(d))
        # The cap bounds the ring (one in-flight segment of slack).
        assert total <= 1200 + 400
        # The newest samples survived; the oldest were compacted away.
        samples = [s for r in history.runs(d) for s in r["samples"]]
        assert samples[-1]["counters"]["c"] == 199
        assert samples[0]["counters"]["c"] > 0

    def test_torn_tail_tolerated(self, tmp_path):
        w = self._writer(tmp_path / "h")
        for i in range(3):
            w.append({"counters": {"c": i}})
        w.close()
        d = str(tmp_path / "h")
        seg = sorted(os.listdir(d))[-1]
        with open(os.path.join(d, seg), "ab") as f:
            f.write(b'{"record": "sample", "seq": 99, "t"')
        samples = [s for r in history.runs(d) for s in r["samples"]]
        assert [s["counters"]["c"] for s in samples] == [0, 1, 2]

    def test_respawn_continues_numbering_and_splits_runs(self, tmp_path):
        d = str(tmp_path / "h")
        w1 = self._writer(tmp_path / "h")
        w1.append({"counters": {"done": 100}})
        w1.close()
        first = set(os.listdir(d))
        w2 = self._writer(tmp_path / "h")
        w2.append({"counters": {"done": 5}})
        w2.close()
        assert first < set(os.listdir(d))  # a NEW segment, never reuse
        # Same test process = same pid, so the reader welds the runs (the
        # clock IS comparable); a real respawn changes pid and splits.
        recs = history.read_records(d)
        headers = [r for r in recs if r["record"] == "header"]
        assert len(headers) == 2
        # Fake the respawn by rewriting the second header's pid.
        seg = sorted(n for n in os.listdir(d))[-1]
        path = os.path.join(d, seg)
        lines = open(path, "rb").read().splitlines()
        h = json.loads(lines[0])
        h["pid"] = h["pid"] + 1
        lines[0] = json.dumps(h).encode()
        open(path, "wb").write(b"\n".join(lines) + b"\n")
        rs = history.runs(d)
        assert len(rs) == 2

    def test_window_rate_sums_per_run_deltas(self, tmp_path):
        d = str(tmp_path / "h")
        os.makedirs(d)

        def seg(index, pid, points):
            lines = [json.dumps({"record": "header", "pid": pid,
                                 "source": "t", "anchor_perf_s": 0.0,
                                 "anchor_unix_ns": 1})]
            for i, (t, v) in enumerate(points):
                lines.append(json.dumps({
                    "record": "sample", "seq": i + 1, "t": t,
                    "counters": {"jobs_completed_total": v},
                }))
            with open(os.path.join(d, f"seg-{index:08d}.jsonl"), "w") as f:
                f.write("\n".join(lines) + "\n")

        seg(0, 100, [(0.0, 0.0), (10.0, 100.0)])
        seg(1, 200, [(3.0, 0.0), (8.0, 50.0)])  # respawned at zero
        rate, seconds = history.window_rate(d, "jobs_completed_total")
        assert seconds == pytest.approx(15.0)
        assert rate == pytest.approx(150.0 / 15.0)
        assert history.window_rate(d, "missing_counter") is None

    def test_report_renders(self, tmp_path):
        w = self._writer(tmp_path / "h")
        for i in range(5):
            w.append({"counters": {"jobs_completed_total": i * 10},
                      "gauges": {"queue_depth": i},
                      "histograms": {"lat": {"count": i, "sum": i,
                                             "p99": 0.1 * i}}})
        w.close()
        text = history.render_report(str(tmp_path / "h"))
        assert "jobs_completed_total" in text
        assert "queue_depth" in text
        assert "lat" in text
        assert "whole-window rates" in text
        empty = history.render_report(str(tmp_path))  # no segments here
        assert "no history records" in empty

    def test_sampler_feeds_history(self, tmp_path):
        from gol_tpu.serve.metrics import Metrics

        metrics = Metrics()
        metrics.inc("jobs_completed_total", 3)
        w = self._writer(tmp_path / "h")
        s = obs_sampler.ServeSampler(metrics, history=w)
        s.tick()
        metrics.inc("jobs_completed_total", 2)
        s.tick()
        w.close()
        samples = [smp for r in history.runs(str(tmp_path / "h"))
                   for smp in r["samples"]]
        assert [smp["counters"]["jobs_completed_total"]
                for smp in samples] == [3, 5]

    def test_server_defaults_history_off(self, tmp_path):
        srv = GolServer(port=0, journal_dir=str(tmp_path / "j"),
                        sample_interval=0)
        srv.start()
        try:
            assert srv.history is None
            assert srv.sampler.history is None
        finally:
            srv.shutdown()


class TestRouterHistory:
    def test_merged_history_is_monotonic_across_worker_reset(self, tmp_path):
        """The acceptance pin: the DURABLE record of a cumulative series
        never dips through a worker respawn, because the router's history
        tick rides the same MonotonicCounters floors the live merge does."""
        snapshots = {"value": 100.0}

        def stub_http(method, url, body=None, raw=None, timeout=0,
                      headers=None):
            return 200, {"counters":
                         {"jobs_completed_total": snapshots["value"]},
                         "gauges": {}, "histograms": {}}

        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        fleet.attach("http://wa.invalid", "wa")
        router = RouterServer(fleet, port=0, http=stub_http)
        router.start()
        try:
            hdir = str(tmp_path / "router-history")
            router.start_history(hdir, interval=3600)
            router.history_tick()
            snapshots["value"] = 7.0  # the worker respawned: reset to ~0
            router.history_tick()
            snapshots["value"] = 20.0
            router.history_tick()
            series = history.counter_series(hdir, "jobs_completed_total")
            values = [v for run in series for _, v in run]
            assert values == sorted(values), values
            assert values[-1] == pytest.approx(120.0)  # 100 banked + 20
            gauges = [s["gauges"] for r in history.runs(hdir)
                      for s in r["samples"]]
            assert all(g["fleet_workers"] == 1 for g in gauges)
        finally:
            router.shutdown(cascade=False)

    def test_history_off_by_default(self, tmp_path):
        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        router = RouterServer(fleet, port=0)
        try:
            assert router._history is None
            router.history_tick()  # a no-op, never raises
        finally:
            router.httpd.server_close()


class TestBenchDiffHistory:
    @staticmethod
    def _write(d, rate_points):
        os.makedirs(d, exist_ok=True)
        lines = [json.dumps({"record": "header", "pid": 1, "source": "t",
                             "anchor_perf_s": 0.0, "anchor_unix_ns": 1})]
        for i, (t, v) in enumerate(rate_points):
            lines.append(json.dumps({
                "record": "sample", "seq": i + 1, "t": t,
                "counters": {"jobs_completed_total": v},
            }))
        with open(os.path.join(d, "seg-00000000.jsonl"), "w") as f:
            f.write("\n".join(lines) + "\n")

    def test_regression_window_exits_nonzero(self, tmp_path, capsys):
        old = str(tmp_path / "old")
        new = str(tmp_path / "new")
        self._write(old, [(0.0, 0.0), (10.0, 1000.0)])  # 100/s
        self._write(new, [(0.0, 0.0), (10.0, 500.0)])  # 50/s: regressed
        assert bench_diff.main(["--history", old, new]) == 1
        assert "REGRESSION" in capsys.readouterr().out

    def test_within_tolerance_exits_zero(self, tmp_path):
        old = str(tmp_path / "old")
        new = str(tmp_path / "new")
        self._write(old, [(0.0, 0.0), (10.0, 1000.0)])
        self._write(new, [(0.0, 0.0), (10.0, 950.0)])  # -5% < 10%
        assert bench_diff.main(["--history", old, new]) == 0

    def test_missing_counter_is_a_shape_error(self, tmp_path):
        old = str(tmp_path / "old")
        new = str(tmp_path / "new")
        self._write(old, [(0.0, 0.0), (10.0, 1000.0)])
        self._write(new, [(0.0, 0.0), (10.0, 900.0)])
        assert bench_diff.main(
            ["--history", old, new, "--metric", "never_seen_total"]
        ) == 2

    def test_not_a_directory_is_a_shape_error(self, tmp_path):
        new = str(tmp_path / "new")
        self._write(new, [(0.0, 0.0), (10.0, 900.0)])
        assert bench_diff.main(
            ["--history", str(tmp_path / "missing"), new]
        ) == 2


class TestCliValidation:
    """History-flag combinations that would otherwise fail AFTER boot (a
    silently-empty ring, a fleet of boot-crashing workers) must be the
    CLI's `gol: <error>` rc-1 contract, rejected before anything spawns."""

    def _run(self, argv, capsys):
        from gol_tpu import cli

        rc = cli.main(argv)
        return rc, capsys.readouterr().err

    def test_serve_history_needs_the_sampler(self, tmp_path, capsys):
        rc, err = self._run([
            "serve", "--journal-dir", str(tmp_path / "j"),
            "--metrics-history", "--sample-interval", "0",
        ], capsys)
        assert rc == 1 and "gol:" in err and "--sample-interval" in err

    def test_serve_bare_history_needs_a_journal(self, tmp_path, capsys):
        rc, err = self._run(["serve", "--metrics-history"], capsys)
        assert rc == 1 and "gol:" in err and "--journal-dir" in err

    def test_fleet_rejects_history_flags_before_spawning(self, tmp_path,
                                                         capsys):
        rc, err = self._run([
            "fleet", "--workers", "1",
            "--fleet-dir", str(tmp_path / "fleet"),
            "--metrics-history", "--history-bytes", "2048",
        ], capsys)
        assert rc == 1 and "gol:" in err and "--history-bytes" in err
        # Nothing spawned: the fleet dir holds no worker partition/log.
        assert not any((tmp_path / "fleet").glob("w*")), \
            list((tmp_path / "fleet").glob("*"))
        rc, err = self._run([
            "fleet", "--workers", "1",
            "--fleet-dir", str(tmp_path / "fleet2"),
            "--metrics-history", "--sample-interval", "0",
        ], capsys)
        assert rc == 1 and "gol:" in err and "--sample-interval" in err
        assert not any((tmp_path / "fleet2").glob("w*"))
