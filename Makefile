# Reference-workflow parity (Makefile:12-28 of the reference): each target
# produces an ./a.out that runs the matching variant, so muscle-memory
# workflows (`make collective && ./a.out 512 512 grid.txt`) keep working.
# There is nothing to compile ahead of time — the XLA/Mosaic compilation
# happens per-shape at runtime; the native codec builds itself on first use.

VARIANTS := game mpi collective async openmp cuda tpu

.PHONY: all test bench bench-diff serve-smoke tune-smoke obs-smoke pipeline-smoke megabatch-smoke slo-smoke fleet-smoke cache-smoke fleettrace-smoke sparse-smoke macro-smoke autoscale-smoke chaos-smoke storage-smoke control-smoke shard-smoke soak soak-tpu clean $(VARIANTS)

all: tpu

$(VARIANTS):
	@printf '#!/bin/sh\nexec python3 -m gol_tpu "$$@" --variant $@\n' > a.out
	@chmod +x a.out
	@echo "./a.out -> gol_tpu --variant $@"

test:
	python3 -m pytest tests/ -q

bench:
	python3 bench.py

# Regression gate over two BENCH_*.json artifacts of the same suite
# (tools/bench_diff.py): nonzero exit when the headline metric moved in the
# bad direction beyond TOLERANCE (default 10%), so it is CI-able. METRIC
# gates on a flattened nested leaf instead of the headline — the cache
# suite's CI gate rides the warm-hit jobs/sec leaf so hit-path regressions
# fail even when the cold lane moves too, and the wire suite's bytes-on-wire
# headline (text/packed round-trip byte ratio at 2048^2, higher is better —
# a format regression shows up as the ratio collapsing toward 1) gates via
# its nested leaf likewise:
#   make bench-diff OLD=BENCH_r08.json NEW=/tmp/BENCH_r08.json [TOLERANCE=0.1]
#   make bench-diff OLD=BENCH_r11.json NEW=/tmp/BENCH_r11.json \
#       METRIC=lanes.warm.jobs_per_sec
#   make bench-diff OLD=BENCH_r13.json NEW=/tmp/BENCH_r13.json \
#       METRIC=sizes.b2048.bytes.ratio_roundtrip
# The sparse suite's CI gate rides its 2^14^2 dense/sparse per-generation
# ratio leaf (higher is better — an elision/batching regression shows up
# as the ratio collapsing toward the dense floor):
#   make bench-diff OLD=BENCH_r14.json NEW=/tmp/BENCH_r14.json \
#       METRIC=sizes.u16384.ratio_dense_over_sparse
# The autoscale suite's CI gate rides the autoscaled lane's steady-state
# throughput leaf (higher is better) rather than the headline ratio — a
# regression in the scaled-out fleet fails the gate even when the static
# baseline moved with it:
#   make bench-diff OLD=BENCH_r15.json NEW=/tmp/BENCH_r15.json \
#       METRIC=lanes.autoscaled.jobs_per_sec
# The chaos suite's CI gate rides the defended lane's fault-free
# throughput leaf (higher is better) — a defenses-cost regression fails
# even when the off-column baseline moved with it; the degraded-goodput
# floor (>= 0.70x defended under one 30%-faulty hop) is exit-code gated
# inside the suite itself:
#   make bench-diff OLD=BENCH_r16.json NEW=/tmp/BENCH_r16.json \
#       METRIC=lanes.defended.jobs_per_sec
# The storage suite's CI gate rides the compaction-on lane's steady-state
# throughput leaf (higher is better) — the cost of bounding the journal
# must stay invisible; the >= 0.97x on/off ratio and the bounded-footprint
# check are exit-code gated inside the suite itself:
#   make bench-diff OLD=BENCH_r17.json NEW=/tmp/BENCH_r17.json \
#       METRIC=lanes.compaction_on.jobs_per_sec
# The control suite's CI gate rides the two-replica lane's forward
# throughput leaf (higher is better) — a router-tier regression fails
# even when the single-router baseline moved with it; the >= 1.8x
# routers2/routers1 scaling floor is exit-code gated inside the suite
# itself (enforced on hosts with >= 3 usable cores — see the artifact's
# gate stamp):
#   make bench-diff OLD=BENCH_r18.json NEW=/tmp/BENCH_r18.json \
#       METRIC=lanes.routers2.forwards_per_sec
# The shard suite's CI gate rides the n=4 lane's device-time aggregate
# cell-updates/sec leaf (higher is better) — a halo/barrier/checkpoint
# overhead regression or an HRW balance regression inflates the slowest
# worker's CPU makespan and fails the gate even when the n=1 baseline
# moved with it; the >= 2x n4/n1 strong-scaling floor and the
# byte-identical-across-lanes board digest are exit-code gated inside
# the suite itself:
#   make bench-diff OLD=BENCH_r20.json NEW=/tmp/BENCH_r20.json \
#       METRIC=lanes.shard_n4.cell_updates_per_sec
bench-diff:
	@test -n "$(OLD)" && test -n "$(NEW)" || \
		{ echo "usage: make bench-diff OLD=a.json NEW=b.json [TOLERANCE=0.1] [METRIC=dot.path]"; exit 2; }
	python3 tools/bench_diff.py $(OLD) $(NEW) $(if $(TOLERANCE),--tolerance $(TOLERANCE)) $(if $(METRIC),--metric $(METRIC))

# Serving restart-safety smoke (tools/serve_smoke.py): boots `gol serve` on a
# free port, submits 50 jobs across 2 bucket shapes, SIGKILLs it mid-batch,
# restarts on the same journal, and verifies every accepted job ends DONE
# exactly once with oracle-identical results.
serve-smoke:
	python3 tools/serve_smoke.py

# Autotune end-to-end smoke (tools/tune_smoke.py): a tiny CPU search runs,
# persists plans, a fresh process reloads them, and the selected plan's
# output byte-matches the NumPy oracle (empty-cache runs stay byte-identical).
tune-smoke:
	python3 tools/tune_smoke.py

# Observability smoke (tools/obs_smoke.py): a traced run is crashed by a
# fault plan, the flight-recorder JSONL must land and parse, `gol
# trace-report` must render it, and a clean traced run must export
# well-formed Chrome trace JSON.
obs-smoke:
	python3 tools/obs_smoke.py

# Resident mega-batch smoke (tools/megabatch_smoke.py): a `gol serve
# --resident-ring` session is SIGKILLed mid-ring, a restart replays the
# journal to every job DONE exactly once, and the resident results are
# byte-identical to a classic depth-1 server's.
megabatch-smoke:
	python3 tools/megabatch_smoke.py

# Async-pipeline smoke (tools/pipeline_smoke.py): a checkpointed run with the
# async writer is SIGKILLed mid-background-payload-write, auto-resume must be
# byte-identical to an uninterrupted run (and sync/async payloads identical);
# then a depth-2 pipelined serve session drains clean with every job DONE
# exactly once.
pipeline-smoke:
	python3 tools/pipeline_smoke.py

# SLO smoke (tools/slo_smoke.py): an injected slow bucket trips the
# multi-window burn-rate alert; observe-only logs and keeps accepting,
# --slo-shed answers 429 + Retry-After, a SIGUSR1 flight dump carries the
# SLO state provider, and a completed job's timeline decomposes exactly.
slo-smoke:
	python3 tools/slo_smoke.py

# Fleet crash/rebalance smoke (tools/fleet_smoke.py): a 3-worker
# `gol fleet` takes 100 jobs across 3 buckets, one worker is SIGKILLed
# mid-batch (its partition replays/rebalances to exactly-once fleet-wide,
# results oracle-identical), and a cascaded SIGTERM drain exits clean.
fleet-smoke:
	python3 tools/fleet_smoke.py

# Result-cache smoke (tools/cache_smoke.py): a real `gol serve
# --result-cache` session is killed and restarted — the resubmitted board
# must hit the on-disk CAS tier byte-identically to a cache-disabled run,
# and a corrupted CAS entry must evict loudly and re-run correctly.
cache-smoke:
	python3 tools/cache_smoke.py

# Fleet-tracing + metrics-history smoke (tools/fleettrace_smoke.py): a real
# `gol fleet --workers 2` under --trace/--metrics-history takes a Zipf load
# with cache hits, one worker is SIGKILLed mid-load (spillover + respawn),
# and `gol fleet-trace` must stitch ONE valid Perfetto JSON (router + both
# worker pids, >= 1 cross-process flow chain) while `gol history-report`
# renders the router's durable ring with jobs_completed_total monotonic
# through the respawn.
fleettrace-smoke:
	python3 tools/fleettrace_smoke.py

# Sparse-engine smoke (tools/sparse_smoke.py): a glider crossing >= 4 tile
# boundaries is byte-checked against the dense engine + oracle for both
# conventions, then a real `gol serve` running a long sparse job is
# SIGKILLed mid-run and the restart must replay the journaled RLE spec to
# an identical result with exactly one done record.
sparse-smoke:
	python3 tools/sparse_smoke.py

# Macrocell deep-time smoke (tools/macro_smoke.py): the Gosper gun runs
# 10^6 generations on the hash-consed macro engine and its population
# must match the closed-form glider census anchored by a shallow sparse
# run (pop(g) = pop(g0) + 5*(g-g0)/30, same period-30 phase); then a
# fresh-process rerun on the same CAS directory must serve content-tier
# hits and finish with strictly less device work.
macro-smoke:
	python3 tools/macro_smoke.py

# Elastic-fleet smoke (tools/autoscale_smoke.py): a real 1-worker
# `gol fleet --autoscale` under a step load must scale up, survive a
# SIGKILL of a scaled worker mid-load (respawn + replay), finish every
# job oracle-identically, retire back to the 1-worker floor when the
# load stops, and audit exactly-once done records across ALL journal
# partitions — including retired workers'.
autoscale-smoke:
	python3 tools/autoscale_smoke.py

# Chaos smoke (tools/chaos_smoke.py): a real 2-worker `gol fleet --chaos`
# under a seeded plan mixing resets, latency, and GOLP frame corruption,
# plus a SIGKILL mid-load — every accepted job DONE exactly once, sampled
# results oracle-identical through the faulty hop, and the victim's
# circuit breaker observed opening AND re-closing in the durable
# breaker-history ring.
chaos-smoke:
	python3 tools/chaos_smoke.py

# Storage-lifecycle smoke (tools/storage_smoke.py): an injected-pressure
# partition sheds CAS writes then refuses admission with 507 (in-flight
# jobs still land) and recovers unattended; a churn journal compacts to
# snapshot + live file with replay state-identical; a real `gol serve` is
# SIGKILLed at the compaction retire boundary and the restart finishes
# every accepted job with exactly one done record, oracle-identical.
storage-smoke:
	python3 tools/storage_smoke.py

# Control-plane failover smoke (tools/control_smoke.py): a real 2-worker
# `gol fleet --routers 2` takes half its load alternating across both
# routers, the lease-holding router is SIGKILLed mid-load (the survivor
# must win the flock lease, respawn a SIGKILLed worker, and place the
# rest of the load), and the exactly-once audit spans every partition
# journal through both kills.
control-smoke:
	python3 tools/control_smoke.py

# Sharded-universe smoke (tools/shard_smoke.py): a real 3-worker
# `gol fleet` takes one giant-universe shard job (HRW tile ownership,
# halo frames over the packed wire), one worker is SIGKILLed
# mid-super-step — the respawn replays ONLY its own shard's journal from
# the durable super-step — and the final board must be byte-identical to
# an uninterrupted single-process sparse run, with an exactly-once audit
# (one done record per partition, restore records only on the victim).
shard-smoke:
	python3 tools/shard_smoke.py

# Open-ended randomized differential campaigns (tools/soak_*.py docstrings).
soak:
	python3 tools/soak_cpu.py $(or $(SECONDS_CPU),600)

soak-tpu:
	python3 tools/soak_tpu.py $(or $(SECONDS_TPU),600)

# The reference's `clean` removes *.out, which also deletes the output DATA
# files since they share the suffix (reference Makefile:31) — reproduced
# deliberately, minus the surprise: data files are listed explicitly.
clean:
	rm -f a.out game_output.out mpi_output.out collective_output.out \
	      async_output.out openmp_output.out cuda_output.out tpu_output.out
