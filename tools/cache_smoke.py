"""Result-cache smoke: kill/restart across the CAS tier, corrupt, re-run.

The `make cache-smoke` harness, exercising the ISSUE 9 acceptance against
real OS processes:

1. boot `gol serve --result-cache --cache-dir` with a journal; submit one
   board and collect its engine-path result;
2. SIGKILL the server; restart on the same directories; resubmit the SAME
   board — it must be served from the **disk CAS tier** (the memory tier
   died with the process), byte-identical, marked ``cached: disk``;
3. byte-gate: a cache-DISABLED server run of the same board must produce
   the identical grid/generations/exit reason (the cache must be
   invisible in the bytes);
4. corrupt the CAS entry on disk; restart; resubmit — the server must
   evict loudly, RE-RUN the engine, and still answer byte-identically
   (``cache_corrupt_evictions_total`` counts it); the re-run repopulates
   the tier (a further resubmission hits again).

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/cache_smoke.py [--gen-limit 200]
"""

import argparse
import glob
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from gol_tpu.io import text_grid  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(method, url, body=None, timeout=10):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        # Error statuses (the 409 "result not ready" poll) are answers
        # here, not exceptions.
        return err.code, json.loads(err.read())


def _start_server(port: int, journal_dir: str, cache_dir: str | None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [
        sys.executable, "-m", "gol_tpu", "serve",
        "--port", str(port),
        "--journal-dir", journal_dir,
        "--flush-age", "0.05",
    ]
    if cache_dir is not None:
        cmd += ["--result-cache", "--cache-dir", cache_dir]
    proc = subprocess.Popen(
        cmd, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    deadline = time.perf_counter() + 120
    base = f"http://127.0.0.1:{port}"
    while time.perf_counter() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"server died on boot (rc={proc.returncode}):\n"
                + (proc.stdout.read() if proc.stdout else "")
            )
        try:
            status, _ = _http("GET", f"{base}/healthz", timeout=2)
            if status == 200:
                return proc, base
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.2)
    proc.kill()
    raise RuntimeError("server did not become healthy in 120s")


def _submit_and_fetch(base: str, body: dict) -> dict:
    status, payload = _http("POST", f"{base}/jobs", body)
    assert status == 202, f"submit got HTTP {status}: {payload}"
    job_id = payload["id"]
    deadline = time.perf_counter() + 120
    while time.perf_counter() < deadline:
        status, result = _http("GET", f"{base}/result/{job_id}")
        if status == 200:
            return result
        assert status == 409, f"result fetch got HTTP {status}: {result}"
        time.sleep(0.05)
    raise RuntimeError(f"job {job_id} did not finish in 120s")


def _metrics(base: str) -> dict:
    status, snap = _http("GET", f"{base}/metrics?format=json")
    assert status == 200
    return snap["counters"]


def _same_answer(a: dict, b: dict) -> bool:
    return (a["grid"] == b["grid"]
            and a["generations"] == b["generations"]
            and a["exit_reason"] == b["exit_reason"])


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--gen-limit", type=int, default=200)
    args = parser.parse_args()

    root = tempfile.mkdtemp(prefix="gol_cache_smoke_")
    journal_dir = os.path.join(root, "journal")
    cache_dir = os.path.join(root, "cache")
    plain_journal = os.path.join(root, "journal_nocache")
    rng = np.random.default_rng(1234)
    board = rng.integers(0, 2, size=(64, 64), dtype=np.uint8)
    body = {
        "width": 64, "height": 64,
        "cells": text_grid.encode(board).decode("ascii"),
        "gen_limit": args.gen_limit,
    }
    proc = None
    try:
        # 1. Engine path populates the tiers.
        port = _free_port()
        proc, base = _start_server(port, journal_dir, cache_dir)
        engine_result = _submit_and_fetch(base, body)
        assert "cached" not in engine_result, \
            f"first run must take the engine path: {engine_result}"
        counters = _metrics(base)
        assert counters.get("cache_misses_total", 0) >= 1, counters
        entries = glob.glob(os.path.join(cache_dir, "*", "*.json"))
        assert entries, "CAS tier wrote no entry"
        print(f"cache-smoke: engine run done "
              f"({engine_result['generations']} generations; CAS entry "
              f"{os.path.basename(entries[0])})")

        # 2. SIGKILL; restart; the resubmission must hit the DISK tier.
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        port = _free_port()
        proc, base = _start_server(port, journal_dir, cache_dir)
        hit_result = _submit_and_fetch(base, body)
        assert hit_result.get("cached") == "disk", \
            f"post-restart resubmit must hit the CAS tier: {hit_result}"
        assert _same_answer(engine_result, hit_result), \
            "CAS hit is not byte-identical to the engine result"
        counters = _metrics(base)
        assert counters.get("cache_hits_total_disk", 0) >= 1, counters
        print("cache-smoke: restart + resubmit hit the CAS tier, "
              "byte-identical")

        # 3. Byte-gate against a cache-DISABLED server.
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
        port = _free_port()
        proc, base = _start_server(port, plain_journal, None)
        plain_result = _submit_and_fetch(base, body)
        assert "cached" not in plain_result
        assert _same_answer(plain_result, hit_result), \
            "cached answer differs from a cache-disabled server's"
        print("cache-smoke: cache-disabled run byte-identical")

        # 4. Corrupt the CAS entry: loud evict + correct re-run. The
        # default payload is the packed wire sidecar (.golp); flip cell
        # bits in its payload without touching the meta commit point —
        # the CRC gate must catch the defect on read.
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
        meta_path = entries[0]
        sidecar = meta_path[: -len(".json")] + ".golp"
        with open(sidecar, "r+b") as f:
            f.seek(-4, os.SEEK_END)
            tail = f.read(4)
            f.seek(-4, os.SEEK_END)
            f.write(bytes(b ^ 0xFF for b in tail))
        port = _free_port()
        proc, base = _start_server(port, journal_dir, cache_dir)
        rerun_result = _submit_and_fetch(base, body)
        assert "cached" not in rerun_result, \
            f"corrupt entry must force a re-run: {rerun_result}"
        assert _same_answer(rerun_result, engine_result), \
            "re-run after corruption is not byte-identical"
        counters = _metrics(base)
        assert counters.get("cache_corrupt_evictions_total", 0) >= 1, counters
        # The re-run repopulated the tier: the next resubmit hits again.
        again = _submit_and_fetch(base, body)
        assert again.get("cached") in ("memory", "disk"), again
        print("cache-smoke: corrupt entry evicted loudly, re-run "
              "byte-identical, tier repopulated")

        status, _ = _http("POST", f"{base}/drain", {})
        assert status == 200
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
        proc = None
        print("cache-smoke: PASS")
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
