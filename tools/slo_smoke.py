"""SLO end-to-end smoke: burn-rate alerting, shedding, and the flight dump.

The `make slo-smoke` harness, against real `gol serve` processes:

1. boot a server in **observe-only** mode (the default) with a deliberately
   tight p99 latency objective (--slo-latency-p99) and a --trace dir (arms
   the flight recorder);
2. inject a **slow bucket**: jobs whose batches take far longer than the
   objective (big boards, deep generation limits — plus the first-dispatch
   compile, which is exactly the kind of latency a tight SLO must catch);
3. wait for ``GET /slo`` to report the latency burn **critical** on every
   window (the multi-window rule);
4. observe-only contract: submissions are STILL 202-accepted, the server
   merely logs the critical burn;
5. ``kill -USR1`` the server: the flight dump must carry the ``slo`` state
   record (the state provider), and ``gol slo-report <dump>`` must render;
6. restart with ``--slo-shed``: once the burn is critical again, POST /jobs
   must answer **429 with a Retry-After header** until the burn clears;
7. along the way, a completed job's ``GET /jobs/<id>/timeline`` must
   decompose: segment sum == total_seconds exactly.

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/slo_smoke.py
"""

import argparse
import glob
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gol_tpu.io import text_grid  # noqa: E402

SLOW_SIDE = 128
SLOW_GENS = 20000
TARGET_S = 0.05


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(method, url, body=None, timeout=10):
    """(status, parsed json, headers) — HTTPError is a reply, not a crash."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read())
        except ValueError:
            payload = {}
        return e.code, payload, dict(e.headers)


def _start_server(port, journal_dir, trace_dir, shed):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    argv = [
        sys.executable, "-m", "gol_tpu", "serve",
        "--port", str(port),
        "--journal-dir", journal_dir,
        "--flush-age", "0.02",
        "--slo-latency-p99", str(TARGET_S),
        "--sample-interval", "0.25",
        "--trace", trace_dir,
    ]
    if shed:
        argv.append("--slo-shed")
    proc = subprocess.Popen(
        argv, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    base = f"http://127.0.0.1:{port}"
    deadline = time.perf_counter() + 120
    while time.perf_counter() < deadline:
        if proc.poll() is not None:
            out, _ = proc.communicate()
            raise RuntimeError(
                f"server died on boot rc={proc.returncode}:\n{out[-3000:]}")
        try:
            status, _, _ = _http("GET", f"{base}/healthz", timeout=2)
            if status == 200:
                return proc
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.1)
    proc.kill()
    raise RuntimeError("server did not become healthy within 120s")


def _stop(proc):
    if proc is None or proc.poll() is not None:
        return ""
    proc.send_signal(signal.SIGTERM)
    try:
        out, _ = proc.communicate(timeout=60)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
    return out or ""


def _submit_slow(base, n=3):
    ids = []
    for i in range(n):
        board = text_grid.generate(SLOW_SIDE, SLOW_SIDE, seed=500 + i)
        status, payload, _ = _http("POST", f"{base}/jobs", {
            "width": SLOW_SIDE, "height": SLOW_SIDE,
            "cells": text_grid.encode(board).decode("ascii"),
            "gen_limit": SLOW_GENS,
        })
        if status != 202:
            raise RuntimeError(
                f"slow-bucket submit rejected HTTP {status}: {payload}")
        ids.append(payload["id"])
    return ids


def _wait_done(base, ids, timeout=300):
    deadline = time.perf_counter() + timeout
    pending = set(ids)
    while pending and time.perf_counter() < deadline:
        for job_id in list(pending):
            status, payload, _ = _http("GET", f"{base}/jobs/{job_id}")
            if status == 200 and payload["state"] == "done":
                pending.discard(job_id)
            elif status == 200 and payload["state"] in ("failed", "cancelled"):
                raise RuntimeError(f"job {job_id} ended {payload['state']}")
        if pending:
            time.sleep(0.2)
    if pending:
        raise RuntimeError(f"{len(pending)} slow job(s) never completed")


def _wait_critical(base, timeout=30):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        status, slo, _ = _http("GET", f"{base}/slo")
        if status == 200 and slo.get("status") == "critical":
            return slo
        time.sleep(0.25)
    raise RuntimeError(
        f"SLO never went critical within {timeout}s (last: {slo})")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.parse_args(argv)
    workdir = tempfile.mkdtemp(prefix="gol-slo-smoke-")
    rc = 1
    proc = None
    try:
        # -- phase A: observe-only ------------------------------------------
        port = _free_port()
        base = f"http://127.0.0.1:{port}"
        trace_dir = os.path.join(workdir, "trace-a")
        proc = _start_server(port, os.path.join(workdir, "journal-a"),
                             trace_dir, shed=False)
        print(f"slo-smoke: observe-only server up on {base} "
              f"(p99 target {TARGET_S}s)")
        ids = _submit_slow(base)
        _wait_done(base, ids)

        # Timeline decomposition of a completed slow job.
        status, tl, _ = _http("GET", f"{base}/jobs/{ids[0]}/timeline")
        if status != 200 or tl.get("total_seconds") is None:
            print(f"slo-smoke: timeline missing: HTTP {status} {tl}")
            return 1
        seg_sum = sum(v for k, v in tl["segments"].items() if k != "journal")
        if abs(seg_sum - tl["total_seconds"]) > 1e-9:
            print(f"slo-smoke: timeline segments {seg_sum} != total "
                  f"{tl['total_seconds']}")
            return 1
        print(f"slo-smoke: timeline decomposes ({len(tl['segments'])} "
              f"segments, total {tl['total_seconds'] * 1e3:.0f} ms)")

        slo = _wait_critical(base)
        burn = next(o for o in slo["objectives"]
                    if o["name"] == "latency_p99_normal")
        print(f"slo-smoke: latency burn critical "
              f"(binding burn {burn['burn']}, windows "
              f"{[w['burn'] for w in burn['windows'].values()]})")
        if slo["shed"]["enabled"] or slo["shed"]["active"]:
            print(f"slo-smoke: observe-only server claims shedding: {slo['shed']}")
            return 1

        # Observe-only: a critical burn must NOT shed.
        board = text_grid.generate(32, 32, seed=1)
        status, payload, _ = _http("POST", f"{base}/jobs", {
            "width": 32, "height": 32,
            "cells": text_grid.encode(board).decode("ascii"), "gen_limit": 2,
        })
        if status != 202:
            print(f"slo-smoke: observe-only server shed a job "
                  f"(HTTP {status}: {payload})")
            return 1
        print("slo-smoke: observe-only accepted under critical burn (202)")

        # SIGUSR1 -> flight dump with the slo state record.
        proc.send_signal(signal.SIGUSR1)
        dump = None
        deadline = time.perf_counter() + 15
        while time.perf_counter() < deadline and dump is None:
            for path in glob.glob(os.path.join(trace_dir, "flight-*.jsonl")):
                with open(path, "rb") as f:
                    for line in f.read().split(b"\n"):
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if (rec.get("record") == "state"
                                and rec.get("name") == "slo"):
                            dump = (path, rec)
            time.sleep(0.25)
        if dump is None:
            print(f"slo-smoke: no flight dump with an slo state record "
                  f"in {trace_dir}")
            return 1
        path, rec = dump
        if rec.get("status") != "critical":
            print(f"slo-smoke: flight slo state is {rec.get('status')!r}, "
                  "expected critical")
            return 1
        print(f"slo-smoke: flight dump carries SLO state ({path})")
        report = subprocess.run(
            [sys.executable, "-m", "gol_tpu", "slo-report", path],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        if report.returncode != 0 or "critical" not in report.stdout:
            print(f"slo-smoke: gol slo-report failed on the dump: "
                  f"rc={report.returncode}\n{report.stdout}{report.stderr}")
            return 1
        out = _stop(proc)
        proc = None
        if "CRITICAL" not in out or "observe-only" not in out:
            print(f"slo-smoke: observe-only server never logged the "
                  f"critical burn:\n{out[-2000:]}")
            return 1
        print("slo-smoke: observe-only server logged the burn")

        # -- phase B: --slo-shed --------------------------------------------
        port = _free_port()
        base = f"http://127.0.0.1:{port}"
        proc = _start_server(port, os.path.join(workdir, "journal-b"),
                             os.path.join(workdir, "trace-b"), shed=True)
        print(f"slo-smoke: shedding server up on {base}")
        ids = _submit_slow(base)
        _wait_done(base, ids)
        _wait_critical(base)
        status, payload, headers = _http("POST", f"{base}/jobs", {
            "width": 32, "height": 32,
            "cells": text_grid.encode(board).decode("ascii"), "gen_limit": 2,
        })
        if status != 429:
            print(f"slo-smoke: shedding server answered HTTP {status} "
                  f"under critical burn (want 429): {payload}")
            return 1
        retry_after = headers.get("Retry-After")
        if not retry_after or int(retry_after) <= 0:
            print(f"slo-smoke: 429 without a usable Retry-After "
                  f"(headers: {headers})")
            return 1
        print(f"slo-smoke: shed with 429 + Retry-After {retry_after}s")
        _stop(proc)
        proc = None

        print("slo-smoke: PASS — burn tripped on the injected slow bucket, "
              "observe-only logged + accepted, --slo-shed 429'd with "
              "Retry-After, flight dump carried SLO state, timeline "
              "decomposed exactly")
        rc = 0
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.communicate()
        if rc == 0:
            shutil.rmtree(workdir, ignore_errors=True)
        else:
            print(f"slo-smoke: artifacts kept in {workdir}")


if __name__ == "__main__":
    sys.exit(main())
