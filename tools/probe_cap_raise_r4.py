"""Can _MAX_WORDS_T be raised? The r4 width-continuous band target changed
the cap's premise: the r3 note said 16384 words "fails at Mosaic compile
under either target", but the r4 VMEM probe compiled it under the 1MB
target (benchmarks/vmem_probe_r4.json, the 'unexpectedly OK' entry). A
doubled cap doubles the widest grid the rows-only default mesh serves at
full speed (VERDICT r3 missing #1's residual).

This probes widths 12288..32768 words across every temporal form:
compile + EXECUTE + match vs the jnp adder network, plus a marginal-rate
spot check so a raised cap doesn't land on a compiling-but-slow config.

    python tools/probe_cap_raise_r4.py   # -> benchmarks/cap_raise_r4.json
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from gol_tpu.ops import packed_math
from gol_tpu.ops import stencil_packed as sp
from gol_tpu.parallel.mesh import PROXY_2D, SINGLE_DEVICE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "benchmarks", "cap_raise_r4.json")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _force(x):
    int(np.asarray(x[0, 0]))


def main() -> None:
    assert jax.default_backend() == "tpu"
    height = 512
    results = []
    # Temporarily lift the cap so supports_multi admits the probe widths.
    sp._MAX_WORDS_T = 64 << 10
    rng = np.random.default_rng(3)
    for nwords in (12288, 16384, 24576, 32768):
        host = rng.integers(0, np.iinfo(np.uint32).max, size=(height, nwords),
                            dtype=np.uint32, endpoint=True)
        words = jnp.asarray(host)
        # Ground truth: the jnp adder network (identical math, independent
        # lowering — XLA:TPU elementwise vs the Mosaic kernel).
        want = words
        for _ in range(sp.TEMPORAL_GENS):
            want = packed_math.evolve_torus_words(want)
        want = np.asarray(want)
        entry = {"nwords": nwords, "height": height,
                 "target": sp._bandt_target(height, nwords),
                 "band": sp._pick_band(height, nwords,
                                       sp._bandt_target(height, nwords))}
        for name, fn in (
            ("t", lambda w: sp._step_t(w)),
            ("rows", lambda w: sp._distributed_step_multi(w, SINGLE_DEVICE)),
            ("split2d", lambda w: sp._distributed_step_multi(w, PROXY_2D)),
        ):
            t0 = time.time()
            try:
                new = fn(words)[0]
                ok = bool(np.array_equal(np.asarray(new), want))
                entry[name] = {"ok": ok, "secs": round(time.time() - t0, 1)}
                log(f"{nwords}w {name}: {'MATCH' if ok else 'MISMATCH'} "
                    f"({time.time()-t0:.0f}s)")
            except Exception as e:  # noqa: BLE001
                entry[name] = {"ok": False,
                               "err": f"{type(e).__name__}: {str(e)[-300:]}"}
                log(f"{nwords}w {name}: FAIL {type(e).__name__} "
                    f"({time.time()-t0:.0f}s)")
        # Marginal rate for the single-device form (is the config fast?).
        if entry["t"].get("ok"):
            step = jax.jit(
                lambda w, n: jax.lax.fori_loop(
                    0, n, lambda i, x: sp._step_t(x)[0], w),
                static_argnums=1)
            _force(step(words, 2))
            t0 = time.perf_counter(); _force(step(words, 10)); ta = time.perf_counter() - t0
            t0 = time.perf_counter(); _force(step(words, 40)); tb = time.perf_counter() - t0
            per_pass = (tb - ta) / 30
            entry["cells_per_s"] = round(
                height * nwords * 32 * sp.TEMPORAL_GENS / per_pass)
            log(f"  rate: {entry['cells_per_s']/1e12:.2f} Tcells/s")
        results.append(entry)
        with open(OUT, "w") as f:
            json.dump({"purpose": "raise _MAX_WORDS_T? compile+execute+rate "
                                  "past the r3 cap", "probes": results},
                      f, indent=1)
            f.write("\n")
    log("wrote", OUT)


if __name__ == "__main__":
    main()
