"""Sharded-universe smoke: SIGKILL a shard worker mid-super-step.

The `make shard-smoke` harness, exercising the ISSUE 18 acceptance
end-to-end against real OS processes:

1. boot ``gol fleet --workers 3`` on a fresh ``--fleet-dir`` (3 journal
   partitions + the membership manifest);
2. submit ONE giant-universe job with ``"shard": true`` — the router's
   leader-only shard coordinator partitions the tile grid across all 3
   workers by HRW, drives super-steps over real HTTP halo frames, and
   journals per-owner checkpoints into each worker's OWN partition;
3. wait until the job is past its first durable checkpoint, then SIGKILL
   the worker owning the most live tiles, mid-super-step;
4. the fleet health loop respawns the victim on the SAME partition; the
   coordinator rewinds the survivors to the durable super-step in memory
   and restores the victim from its shard journal — the victim replays
   ONLY its own shard (restore records must appear on it and nowhere
   else);
5. the finished board must be byte-identical (RLE text, generations,
   exit_reason) to an uninterrupted single-process `simulate_sparse` run
   of the same spec;
6. exactly-once audit across ALL partition shard journals: every hosting
   partition holds exactly ONE done record for the job, and the job's
   recovery counter shows the kill was actually exercised;
7. SIGTERM the fleet: the cascaded drain must exit rc 0 with every
   worker pid gone.

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/shard_smoke.py [--gen-limit 80] [--kill-at 10]
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from gol_tpu.config import GameConfig  # noqa: E402
from gol_tpu.shard.partition import Partition  # noqa: E402
from gol_tpu.sparse import SparseBoard, TileMemo, simulate_sparse  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TILE = 256
UNIVERSE = 4096  # 16x16 tiles of 256^2


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _start_fleet(port: int, fleet_dir: str, workers: int = 3):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "gol_tpu", "fleet",
            "--port", str(port),
            "--workers", str(workers),
            "--fleet-dir", fleet_dir,
            "--health-interval", "0.5",
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    deadline = time.perf_counter() + 300
    base = f"http://127.0.0.1:{port}"
    while time.perf_counter() < deadline:
        if proc.poll() is not None:
            out, _ = proc.communicate()
            raise RuntimeError(
                f"fleet died on boot rc={proc.returncode}:\n{out[-4000:]}"
            )
        try:
            status, payload = _http("GET", f"{base}/healthz", timeout=2)
            if status == 200 and payload.get("fleet", {}).get("workers") == workers:
                return proc
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.2)
    proc.kill()
    raise RuntimeError("fleet did not become healthy within 300s")


def _glider_board() -> SparseBoard:
    """16 gliders spread over the 16x16 tile grid, a few on tile edges so
    halo frames carry live rings across worker boundaries."""
    glider = np.zeros((3, 3), dtype=np.uint8)
    glider[0, 1] = glider[1, 2] = glider[2, 0] = glider[2, 1] = glider[2, 2] = 1
    board = SparseBoard(UNIVERSE, UNIVERSE, TILE)
    for i in range(4):
        for j in range(4):
            arr = np.zeros((TILE, TILE), dtype=np.uint8)
            if (i + j) % 3 == 0:
                arr[1:4, 120:123] = glider  # top edge: live halo ring
            else:
                arr[120:123, 120:123] = glider
            board.set_tile((2 + 3 * i, 2 + 3 * j), arr)
    return board


def _shard_records(fleet_dir: str, job_id: str) -> dict:
    """worker partition -> list of shard-journal records for the job."""
    out = {}
    for name in sorted(os.listdir(fleet_dir)):
        path = os.path.join(fleet_dir, name, f"shard-{job_id}.jsonl")
        if not os.path.isfile(path):
            continue
        recs = []
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    recs.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # torn tail — the engine tolerates it, so do we
        out[name] = recs
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--gen-limit", type=int, default=80)
    parser.add_argument(
        "--kill-at", type=int, default=10,
        help="SIGKILL the victim once the coordinator reports this "
        "super-step (past the first durable checkpoint at 8)",
    )
    args = parser.parse_args(argv)

    workdir = tempfile.mkdtemp(prefix="gol-shard-smoke-")
    fleet_dir = os.path.join(workdir, "fleet")
    port = _free_port()
    base = f"http://127.0.0.1:{port}"
    board = _glider_board()
    rle = board.to_rle()

    rc = 1
    proc = None
    try:
        proc = _start_fleet(port, fleet_dir)
        print(f"shard-smoke: 3-worker fleet up on {base}, dir {fleet_dir}")

        status, payload = _http("POST", f"{base}/jobs", {
            "shard": True, "rle": rle, "x": 0, "y": 0,
            "width": UNIVERSE, "height": UNIVERSE, "tile": TILE,
            "convention": "c", "gen_limit": args.gen_limit,
            "check_similarity": False, "checkpoint_every": 8,
        })
        if status != 202:
            print(f"shard-smoke: submit rejected HTTP {status}: {payload}")
            return 1
        job_id = payload["id"]
        workers = payload["workers"]
        print(f"shard-smoke: shard job {job_id} across {workers}")

        # The victim: the worker owning the most live tiles (it must have
        # real shard state to replay). Ownership is the same pure HRW
        # function the coordinator used.
        part = Partition(workers, UNIVERSE // TILE, UNIVERSE // TILE)
        counts = part.counts(board.tiles)
        victim_id = max(counts, key=lambda k: counts[k])

        # Kill mid-super-step, past the first durable checkpoint.
        deadline = time.perf_counter() + 300
        while True:
            if time.perf_counter() > deadline:
                print("shard-smoke: job never reached the kill point")
                return 1
            status, job = _http("GET", f"{base}/jobs/{job_id}", timeout=10)
            if status != 200 or job.get("state") == "failed":
                print(f"shard-smoke: job lost before kill: {status} {job}")
                return 1
            if job.get("state") == "done":
                print(f"shard-smoke: job finished before super-step "
                      f"{args.kill_at}; raise --gen-limit")
                return 1
            if job.get("superstep", 0) >= args.kill_at:
                break
            time.sleep(0.02)
        status, fl = _http("GET", f"{base}/fleet")
        victim = next(w for w in fl["workers"] if w["id"] == victim_id)
        print(f"shard-smoke: SIGKILL {victim_id} (pid {victim['pid']}, "
              f"{counts[victim_id]} live tiles) at super-step "
              f"{job['superstep']} (durable {job['durable_superstep']})")
        os.kill(victim["pid"], signal.SIGKILL)

        # The health loop respawns the victim on its partition; the
        # coordinator recovers to the durable floor and the job finishes.
        deadline = time.perf_counter() + 600
        while True:
            if time.perf_counter() > deadline:
                print("shard-smoke: job never completed after the kill")
                return 1
            try:
                status, job = _http("GET", f"{base}/jobs/{job_id}",
                                    timeout=10)
            except (urllib.error.URLError, OSError):
                time.sleep(0.2)
                continue
            if status != 200 or job.get("state") == "failed":
                print(f"shard-smoke: job died after kill: {status} {job}")
                return 1
            if job.get("state") == "done":
                break
            time.sleep(0.1)
        if job.get("recoveries", 0) < 1:
            print(f"shard-smoke: kill was not exercised (recoveries "
                  f"{job.get('recoveries')})")
            return 1
        status, fl = _http("GET", f"{base}/fleet")
        restarts = sum(w["restarts"] for w in fl["workers"])
        if restarts < 1:
            print(f"shard-smoke: expected a respawned worker: {fl}")
            return 1
        print(f"shard-smoke: job done through the kill "
              f"({job['recoveries']} recovery, {restarts} restart(s))")

        status, result = _http("GET", f"{base}/result/{job_id}",
                               timeout=300)
        if status != 200:
            print(f"shard-smoke: result HTTP {status}: {result}")
            return 1

        # Byte-identity against an uninterrupted single-process sparse run.
        cfg = GameConfig(gen_limit=args.gen_limit, check_similarity=False,
                         convention="c")
        solo = simulate_sparse(_glider_board(), cfg, TileMemo())
        if (result["rle"] != solo.board.to_rle()
                or result["generations"] != solo.generations
                or result["exit_reason"] != solo.exit_reason):
            print(f"shard-smoke: sharded result diverges from solo sparse "
                  f"(gens {result['generations']} vs {solo.generations}, "
                  f"exit {result['exit_reason']} vs {solo.exit_reason}, "
                  f"rle match {result['rle'] == solo.board.to_rle()})")
            return 1
        print(f"shard-smoke: board byte-identical to solo sparse "
              f"({result['generations']} generations, "
              f"{result['exit_reason']})")

        # Drain before the journal audit so every fsync has landed.
        pids = [w["pid"] for w in fl["workers"] if w["pid"]]
        proc.send_signal(signal.SIGTERM)
        try:
            out, _ = proc.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            print("shard-smoke: fleet ignored SIGTERM")
            proc.kill()
            return 1
        if proc.returncode != 0:
            print(f"shard-smoke: fleet exited rc={proc.returncode}:\n"
                  f"{out[-3000:]}")
            return 1
        proc = None
        for pid in pids:
            try:
                os.kill(pid, 0)
                print(f"shard-smoke: worker pid {pid} survived the drain")
                return 1
            except ProcessLookupError:
                pass

        # Exactly-once audit: one done record per hosting partition, and
        # restore records ONLY on the victim (survivors rewind in memory —
        # a restore record elsewhere means somebody replayed a shard that
        # was never lost).
        records = _shard_records(fleet_dir, job_id)
        if set(records) != set(workers):
            print(f"shard-smoke: partitions with shard journals "
                  f"{sorted(records)} != job workers {sorted(workers)}")
            return 1
        bad = False
        for name, recs in records.items():
            dones = [r for r in recs if r.get("kind") == "done"]
            restores = [r for r in recs if r.get("kind") == "restore"]
            if len(dones) != 1:
                print(f"shard-smoke: partition {name} has {len(dones)} "
                      f"done record(s), want exactly 1")
                bad = True
            if name == victim_id and not restores:
                print(f"shard-smoke: victim {name} has no restore record "
                      f"— its shard was never replayed from journal")
                bad = True
            if name != victim_id and restores:
                print(f"shard-smoke: survivor {name} has restore "
                      f"record(s) {restores} — replayed a shard that was "
                      f"never lost")
                bad = True
        if bad:
            return 1
        done_steps = {name: recs[-1]["step"] for name, recs in
                      records.items()
                      if recs and recs[-1].get("kind") == "done"}
        print(f"shard-smoke: PASS — exactly one done record per "
              f"partition {done_steps}, restore only on {victim_id}, "
              "cascaded drain clean")
        rc = 0
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.communicate()
        if rc == 0:
            shutil.rmtree(workdir, ignore_errors=True)
        else:
            print(f"shard-smoke: artifacts kept in {workdir}")


if __name__ == "__main__":
    sys.exit(main())
