"""Observability smoke: traced run -> injected crash -> flight dump -> report.

The `make obs-smoke` harness, exercising the gol_tpu/obs post-mortem story
end-to-end against a real OS process:

1. generate an input and run the CLI with ``--trace DIR`` plus a
   checkpointing fault plan (``kill_at_gen``) — the run crashes mid-flight
   exactly as the recovery harness's victims do;
2. the crashed process must leave a flight-recorder dump
   (``flight-<pid>-<seq>.jsonl``) in DIR whose every line parses as JSON,
   with a header record naming the fault and at least one recorded span;
3. ``gol trace-report`` must render that dump (per-phase table + span
   tree + registry counters);
4. a clean traced run of the same input must export Chrome trace JSON
   (``trace-<pid>.json``) with well-formed ``ph:"X"`` events, and
   ``gol trace-report`` must render that too.

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/obs_smoke.py [--size 64] [--gen-limit 40]
"""

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(*a):
    print("obs-smoke:", *a, file=sys.stderr, flush=True)


def fail(msg):
    log("FAIL:", msg)
    sys.exit(1)


def _run_cli(args, cwd, check=True):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "gol_tpu", *args],
        env=env, cwd=cwd, capture_output=True, text=True, timeout=600,
    )
    if check and proc.returncode != 0:
        fail(f"gol {' '.join(args)} -> rc {proc.returncode}\n{proc.stderr[-2000:]}")
    return proc


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=64)
    ap.add_argument("--gen-limit", type=int, default=40)
    args = ap.parse_args(argv)

    work = tempfile.mkdtemp(prefix="gol_obs_smoke_")
    try:
        inp = os.path.join(work, "input.txt")
        trace_dir = os.path.join(work, "trace")
        _run_cli(["generate", str(args.size), str(args.size),
                  "--seed", "7", "-o", inp], cwd=work)

        # 1-2: traced run crashed by the fault plan at a checkpoint boundary.
        kill_at = max(2, args.gen_limit // 2)
        crash = _run_cli(
            [str(args.size), str(args.size), inp, "--variant", "tpu",
             "--gen-limit", str(args.gen_limit),
             "--checkpoint-every", "2",
             "--checkpoint-dir", os.path.join(work, "ckpt"),
             "--fault-plan", f"kill_at_gen={kill_at}",
             "--trace", trace_dir,
             "--output", os.path.join(work, "crash.out")],
            cwd=work, check=False,
        )
        if crash.returncode == 0:
            fail("fault-plan run exited 0; the injected crash never fired")
        log(f"crashed as planned (rc {crash.returncode})")

        dumps = sorted(glob.glob(os.path.join(trace_dir, "flight-*.jsonl")))
        if not dumps:
            fail(f"no flight-recorder dump in {trace_dir}: "
                 f"{os.listdir(trace_dir) if os.path.isdir(trace_dir) else 'missing'}")
        records = []
        for line in open(dumps[0], "rb").read().split(b"\n"):
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                fail(f"unparseable flight-recorder line: {line[:120]!r}")
        kinds = {r.get("record") for r in records}
        if not {"header", "span", "registry"} <= kinds:
            fail(f"flight dump missing record kinds: got {sorted(kinds)}")
        header = next(r for r in records if r["record"] == "header")
        if "fault" not in header["reason"] and "crash" not in header["reason"]:
            fail(f"dump reason does not name the fault: {header['reason']!r}")
        reg = next(r for r in records if r["record"] == "registry")
        if reg.get("counters", {}).get("checkpoint_saves_total", 0) < 1:
            fail(f"registry snapshot missing checkpoint saves: {reg}")
        log(f"flight dump OK: {dumps[0]} "
            f"({sum(1 for r in records if r['record'] == 'span')} spans)")

        # 3: trace-report renders the flight dump.
        report = _run_cli(["trace-report", dumps[0]], cwd=work)
        if "per-phase" not in report.stdout or "span" not in report.stdout:
            fail(f"trace-report output unexpected:\n{report.stdout[:800]}")
        log("trace-report rendered the flight dump")

        # 4: clean traced run exports Chrome trace JSON.
        clean_dir = os.path.join(work, "trace_clean")
        _run_cli(
            [str(args.size), str(args.size), inp, "--variant", "tpu",
             "--gen-limit", str(args.gen_limit), "--trace", clean_dir,
             "--output", os.path.join(work, "clean.out")],
            cwd=work,
        )
        traces = sorted(glob.glob(os.path.join(clean_dir, "trace-*.json")))
        if not traces:
            fail(f"no Chrome trace export in {clean_dir}")
        doc = json.load(open(traces[0]))
        events = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
        if not events:
            fail(f"no ph:'X' events in {traces[0]}")
        names = {e["name"] for e in events}
        if "cli.execution" not in names:
            fail(f"execution span missing from export: {sorted(names)}")
        report = _run_cli(["trace-report", traces[0]], cwd=work)
        if "cli.execution" not in report.stdout:
            fail(f"trace-report did not render the export:\n{report.stdout[:800]}")
        log(f"chrome export OK: {traces[0]} ({len(events)} events)")
        log("PASS")
        return 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
