"""Storage-lifecycle smoke: quota pressure, tiered shedding, compaction,
SIGKILL-mid-compaction, exactly-once audit — end to end.

The `make storage-smoke` harness, exercising the ISSUE-15 acceptance
against real processes and real files:

1. **Shed order** (in-process server, injected free-bytes): as the
   partition "fills", the watchdog degrades in order — CAS writes shed
   first (cache hit ratio sacrificed, results still served), then
   admission refuses with 507 naming the partition — and every tier
   recovers unattended when space returns;
2. **compaction frees space**: a churn load on a segment-rotating journal
   compacts down to snapshot + live file, replaying state-identical to
   the unbounded log;
3. **SIGKILL mid-compaction** (real `gol serve` subprocess, real signal):
   the fault plan SIGKILLs the server at the compaction retire boundary;
   the restart must finish every accepted job with EXACTLY one done
   record per id across the replay-visible record set, every sampled
   result byte-identical to the NumPy oracle.

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/storage_smoke.py [--jobs 12]
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from gol_tpu import oracle  # noqa: E402
from gol_tpu.config import GameConfig  # noqa: E402
from gol_tpu.io import text_grid  # noqa: E402


def _http(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


def _wait(predicate, timeout=120.0, interval=0.05):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def fail(msg):
    print(f"storage-smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def _submit_board(url, board, gen_limit):
    return _http("POST", url + "/jobs", {
        "width": board.shape[1], "height": board.shape[0],
        "cells": text_grid.encode(board).decode("ascii"),
        "gen_limit": gen_limit,
    })


# ---------------------------------------------------------------------------
# Phase 1: shed order + unattended recovery (in-process, injected free bytes)


def phase_shed_order(workdir):
    from gol_tpu.serve.server import GolServer

    print("phase 1: watchdog sheds in order, recovers unattended",
          flush=True)
    journal_dir = os.path.join(workdir, "shed")
    srv = GolServer(port=0, journal_dir=journal_dir, result_cache=True,
                    cache_dir=os.path.join(journal_dir, "cache"),
                    disk_reserve=1 << 20, sample_interval=0,
                    flush_age=0.01)
    free = {"v": 10 << 30}
    srv.disk_guard._free_fn = lambda: free["v"]
    srv.start()
    try:
        board = text_grid.generate(32, 32, seed=1)
        code, payload = _submit_board(srv.url, board, 20)
        if code != 202:
            fail(f"healthy submit answered {code}")
        first = payload["id"]
        if not _wait(lambda: _http(
                "GET", f"{srv.url}/jobs/{first}")[1].get("state") == "done"):
            fail("healthy job never finished")

        # Tier 1: below the CAS watermark — writes shed, service healthy.
        free["v"] = 3 << 20
        srv.storage_tick()
        if srv.disk_guard.level_name != "shed-cas":
            fail(f"expected shed-cas, got {srv.disk_guard.level_name}")
        board2 = text_grid.generate(32, 32, seed=2)
        code, payload = _submit_board(srv.url, board2, 20)
        if code != 202:
            fail(f"submit under shed-cas answered {code}")
        jid = payload["id"]
        if not _wait(lambda: _http(
                "GET", f"{srv.url}/jobs/{jid}")[1].get("state") == "done"):
            fail("job under shed-cas never finished")
        shed = srv.metrics.snapshot()["counters"].get(
            "cas_writes_shed_total", 0)
        if not shed:
            fail("no CAS write was shed under pressure")

        # Tier 3: below the admission watermark — 507, in-flight lands.
        code, payload = _submit_board(srv.url, text_grid.generate(
            32, 32, seed=3), 500)
        if code != 202:
            fail(f"pre-starve submit answered {code}")
        inflight = payload["id"]
        free["v"] = 1000
        srv.storage_tick()
        code, payload = _submit_board(srv.url, board, 20)
        if code != 507:
            fail(f"expected 507 under full disk, got {code}")
        if payload.get("partition") != journal_dir:
            fail(f"507 body does not name the partition: {payload}")
        if payload.get("free_bytes") != 1000:
            fail(f"507 body does not carry free bytes: {payload}")
        if not _wait(lambda: _http(
                "GET",
                f"{srv.url}/jobs/{inflight}")[1].get("state") == "done"):
            fail("in-flight job did not land during admission refusal")

        # Space returns: recovery with NO operator action.
        free["v"] = 10 << 30
        srv.storage_tick()
        code, _payload = _submit_board(srv.url, board, 20)
        if code != 202:
            fail(f"admission did not recover: {code}")
        transitions = srv.metrics.snapshot()["counters"].get(
            "disk_guard_transitions_total", 0)
        print(f"  shed order OK ({int(shed)} CAS write(s) shed, "
              f"{int(transitions)} guard transition(s), 507 body named "
              f"the partition)", flush=True)
    finally:
        srv.shutdown()
    from gol_tpu.serve.jobs import JobJournal

    state = JobJournal(journal_dir, segment_bytes=0).replay()
    if state.torn_lines:
        fail(f"torn records after pressure cycling: {state.torn_lines}")


# ---------------------------------------------------------------------------
# Phase 2: compaction frees space, replay identical


def phase_compaction(workdir):
    from gol_tpu.serve import compaction
    from gol_tpu.serve.jobs import JobJournal, JobResult, new_job

    print("phase 2: compaction frees space, replay identical", flush=True)
    journal_dir = os.path.join(workdir, "compact")
    journal = JobJournal(journal_dir, segment_bytes=2048)
    for i in range(40):
        job = new_job(16, 16, text_grid.generate(16, 16, seed=i))
        journal.record_submit(job)
        job.result = JobResult(grid=text_grid.generate(16, 16, seed=500 + i),
                               generations=i, exit_reason="gen_limit")
        journal.record_done(job)
    before_bytes = journal.bytes_on_disk()
    before = JobJournal(journal_dir, segment_bytes=0).replay()
    report = journal.compact()
    journal.close()
    if not report.compacted:
        fail("compaction found nothing to fold")
    after = JobJournal(journal_dir, segment_bytes=0).replay()
    if after.results.keys() != before.results.keys():
        fail("compaction changed the replayed result set")
    for k in after.results:
        if not np.array_equal(after.results[k].grid, before.results[k].grid):
            fail(f"compaction changed result bytes for {k}")
    if report.bytes_after >= before_bytes:
        fail(f"compaction freed nothing ({before_bytes} -> "
             f"{report.bytes_after})")
    if compaction.sealed_segments(journal_dir):
        fail("sealed segments survived compaction")
    print(f"  compacted {report.segments_retired} segment(s): "
          f"{before_bytes} -> {report.bytes_after} bytes, "
          f"replay identical", flush=True)


# ---------------------------------------------------------------------------
# Phase 3: SIGKILL mid-compaction on a real server, exactly-once audit


def _boot(journal_dir, faults_spec=None):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    if faults_spec:
        env["GOL_FAULTS"] = faults_spec
    else:
        env.pop("GOL_FAULTS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "gol_tpu", "serve", "--port", "0",
         "--journal-dir", journal_dir,
         "--journal-segment-bytes", "600",
         "--sample-interval", "0.2", "--flush-age", "0.01"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )
    url = None
    deadline = time.perf_counter() + 120
    while time.perf_counter() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("serving on "):
            url = line.split("serving on ", 1)[1].strip()
            break
    if not url:
        proc.kill()
        fail("serve subprocess never printed its URL")
    return proc, url


def phase_sigkill(workdir, njobs):
    print("phase 3: SIGKILL mid-compaction, restart, exactly-once audit",
          flush=True)
    journal_dir = os.path.join(workdir, "kill")
    proc, url = _boot(
        journal_dir, "kill_during_compaction=retire,kill_mode=sigkill")
    boards = {}
    try:
        for i in range(njobs):
            board = text_grid.generate(16, 16, seed=300 + i)
            code, payload = _submit_board(url, board, 8)
            if code != 202:
                fail(f"submit {i} answered {code}")
            boards[payload["id"]] = board
        if not _wait(lambda: proc.poll() is not None, timeout=60):
            fail("the injected SIGKILL never fired")
        if proc.poll() != -signal.SIGKILL:
            fail(f"server exited {proc.poll()}, expected SIGKILL")
        print(f"  server SIGKILLed at the compaction retire boundary "
              f"({len(boards)} job(s) accepted)", flush=True)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.stdout.close()
        proc.wait()

    proc, url = _boot(journal_dir)
    try:
        def all_done():
            return all(_http("GET", f"{url}/jobs/{j}")[1].get("state")
                       == "done" for j in boards)
        if not _wait(all_done):
            fail("restart did not finish every accepted job")
        for job_id, board in list(boards.items())[:5]:
            code, result = _http("GET", f"{url}/result/{job_id}")
            if code != 200:
                fail(f"result fetch for {job_id} answered {code}")
            want = oracle.run(board, GameConfig(gen_limit=8))
            got = text_grid.decode(result["grid"].encode("ascii"), 16, 16)
            if not np.array_equal(got, want.grid):
                fail(f"result for {job_id} differs from the oracle")
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        proc.stdout.close()

    # Exactly-once audit over the replay-visible record set (snapshot +
    # segments newer than it + the live file: compaction.iter_records).
    from gol_tpu.serve import compaction
    from gol_tpu.serve.jobs import JobJournal

    state = JobJournal(journal_dir, segment_bytes=0).replay()
    if state.results.keys() != set(boards):
        fail(f"replay results {len(state.results)} != accepted "
             f"{len(boards)}")
    if state.pending or state.torn_lines:
        fail(f"replay left pending={len(state.pending)} "
             f"torn={state.torn_lines}")
    done_counts = {}
    for rec in compaction.iter_records(journal_dir):
        if rec.get("event") == "done":
            done_counts[rec["id"]] = done_counts.get(rec["id"], 0) + 1
    if set(done_counts) != set(boards):
        fail("done-record id set differs from the accepted set")
    dupes = {k: n for k, n in done_counts.items() if n != 1}
    if dupes:
        fail(f"done records not exactly-once: {dupes}")
    print(f"  exactly-once audit OK: {len(done_counts)} done record(s), "
          f"one per accepted job, oracle-identical samples", flush=True)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--jobs", type=int, default=12,
                        help="jobs for the SIGKILL phase (default 12)")
    args = parser.parse_args()
    workdir = tempfile.mkdtemp(prefix="gol-storage-smoke-")
    try:
        phase_shed_order(workdir)
        phase_compaction(workdir)
        phase_sigkill(workdir, args.jobs)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    print("storage-smoke: PASS", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
