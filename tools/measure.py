"""TPU measurement battery — all protocol revisions, one tool.

Consolidates the accreted per-round scripts (measure_r3.py, measure_r4.py,
measure_r5.py, measure_block_r5.py — now thin shims over this module) under
a ``--rev`` flag. The protocol lineage, documented in benchmarks/README.md:

- **r3**: interleaved chained marginals in one process; plus the one-off
  probes (h2d/d2h codec + transfer decomposition, config5 end-to-end).
- **r4**: published ratios become MEDIANS across >= 5 fresh-process
  sessions (the attach tunnel's chip throughput drifts ±35% between
  processes); chains lengthened so the two-length subtraction amortizes the
  ~90 ms dispatch floor to < 2%; best-effort device time via xprof.
- **r5**: r4 plus the ``single_fast`` path (post-fast-flag engine pass) as
  the honest single-chip denominator.

Artifacts land in benchmarks/ with the rev in the filename, so documented
commands — and round-over-round comparisons — keep working:

    python tools/measure.py [--rev 5] session <size>
    python tools/measure.py [--rev 5] compare <size> [sessions=5]
    python tools/measure.py [--rev 5] podshard [sessions=5]
    python tools/measure.py --rev 3 h2d|d2h|config5|compare32k
    python tools/measure.py block [size] [gens] [blocks...]
    python tools/measure.py all

``block`` is the termination-block A/B (formerly measure_block_r5.py); it
now drives the engine's per-runner ``termination_block`` plan parameter
(gol_tpu/tune/space.EnginePlan) instead of mutating a module global.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# The one nearest-rank median (sorted[n // 2], the upper median on even
# counts) — shared with the serving histograms' percentile math via
# gol_tpu/obs/registry.py instead of re-derived here per call site. The
# published artifacts are byte-stable: same rule, one definition.
from gol_tpu.obs.registry import median

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "benchmarks")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _host_words(h: int, w: int, seed: int = 42) -> np.ndarray:
    rng = np.random.default_rng(seed)
    grid = rng.integers(0, 2, size=(h, w), dtype=np.uint8)
    return np.packbits(grid, axis=1, bitorder="little").view(np.uint32)


def _force(x) -> None:
    # block_until_ready is unreliable over the attach tunnel; a scalar
    # readback is the only dependable completion barrier.
    int(np.asarray(x[0, 0]))


def _write(name: str, payload: dict) -> None:
    path = os.path.join(OUT, name)
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    log("wrote", path)


def _device_time_per_pass(fn, words, n: int):
    """Best-effort: total TPU device time for one n-pass chain, via xprof.

    Returns ms per pass or None if the trace/parse path is unavailable.
    """
    import glob
    import tempfile

    from gol_tpu.obs import profiler

    try:
        from xprof.convert import raw_to_tool_data
    except Exception:
        return None
    try:
        with tempfile.TemporaryDirectory() as td:
            # The guarded capture (gol_tpu/obs/profiler.py): a profiler
            # start failure degrades to "no device time", never a dead
            # session — the same implementation behind the CLI's --profile.
            with profiler.capture(td) as started:
                _force(fn(words, n))
            if not started:
                return None
            planes = glob.glob(os.path.join(td, "**", "*.xplane.pb"),
                               recursive=True)
            if not planes:
                return None
            data, _ = raw_to_tool_data.xspace_to_tool_data(
                planes, "op_profile", {}
            )
            if isinstance(data, bytes):
                data = data.decode("utf-8", "replace")
            # op_profile's byProgram rawTime is total DEVICE picoseconds in
            # the traced window — the chain dominates it (dispatch and the
            # tunnel never appear in device time).
            raw_ps = json.loads(data)["byProgram"]["metrics"]["rawTime"]
            return raw_ps / 1e9 / n
    except Exception as e:  # noqa: BLE001 - best effort, never fail the session
        log("device-time parse failed:", type(e).__name__, str(e)[:120])
        return None


# ---------------------------------------------------------------------------
# r4/r5 protocol: fresh-process sessions of interleaved chained marginals.
# ---------------------------------------------------------------------------


def session(size: int, rev: int = 5, reps: int = 3, trace: bool = True) -> dict:
    """One process's interleaved A/B/C: single-chip temporal vs rows-only
    mesh form vs split-edge 2D form, marginal over two chain lengths. Rev 5
    adds the ``single_fast`` (post-fast-flag) denominator."""
    import jax
    import jax.numpy as jnp

    from gol_tpu.ops import stencil_packed as sp
    from gol_tpu.parallel.mesh import PROXY_2D, SINGLE_DEVICE

    assert jax.default_backend() == "tpu", jax.default_backend()
    T = sp.TEMPORAL_GENS
    words = jnp.asarray(_host_words(size, size))

    def chain(step):
        def fn(w, n):
            return jax.lax.fori_loop(0, n, lambda i, x: step(x), w)
        return jax.jit(fn, static_argnums=1)

    paths = {
        # 'single' is the r4 denominator (exact per-generation flags), kept
        # for round-over-round comparability; 'single_fast' (rev 5) is what
        # the engine actually runs on one chip since the fast-flag passes
        # (packed_step_multi -> _step_t_fast) — the honest denominator for
        # "what does a pod chip pay vs a single chip".
        "single": chain(lambda w: sp._step_t(w)[0]),
        "rows": chain(lambda w: sp._distributed_step_multi(w, SINGLE_DEVICE)[0]),
        "split2d": chain(lambda w: sp._distributed_step_multi(w, PROXY_2D)[0]),
    }
    if rev >= 5:
        paths["single_fast"] = chain(lambda w: sp._step_t_fast(w)[0])
    # Chain lengths: >= 200 passes of margin, scaled down for the larger grid.
    n1, n2 = (50, 250) if size <= 16384 else (25, 100)

    # Compile + warm every path before any timing.
    for name, fn in paths.items():
        t0 = time.perf_counter()
        _force(fn(words, 2))
        log(f"  warm {name}: {time.perf_counter() - t0:.0f}s")

    def timed(fn, n):
        t0 = time.perf_counter()
        _force(fn(words, n))
        return time.perf_counter() - t0

    # Discard round: the first full-length timed pass after compile absorbs
    # one-time upload/init effects (observed as negative marginals otherwise).
    for fn in paths.values():
        timed(fn, n1)

    rates = {k: [] for k in paths}
    for rep in range(reps):
        # Interleave across paths at both lengths within each rep.
        t1 = {k: timed(fn, n1) for k, fn in paths.items()}
        t2 = {k: timed(fn, n2) for k, fn in paths.items()}
        for k in paths:
            per_pass = (t2[k] - t1[k]) / (n2 - n1)
            rates[k].append(size * size * T / per_pass)
        log(f"  rep {rep}: " + ", ".join(
            f"{k}={rates[k][-1] / 1e12:.2f}T" for k in paths))

    med = {k: median(v) for k, v in rates.items()}
    out = {
        "size": size,
        "reps": reps,
        "chain_lengths": [n1, n2],
        "cells_per_s": {k: [round(r, 0) for r in v] for k, v in rates.items()},
        "ratio_rows": round(med["rows"] / med["single"], 4),
        "ratio_2d": round(med["split2d"] / med["single"], 4),
        "single_median_cells_per_s": round(med["single"], 0),
    }
    if rev >= 5:
        out["ratio_rows_vs_fast"] = round(med["rows"] / med["single_fast"], 4)
        out["ratio_2d_vs_fast"] = round(med["split2d"] / med["single_fast"], 4)
        out["single_fast_median_cells_per_s"] = round(med["single_fast"], 0)
    if trace:
        dt = {k: _device_time_per_pass(fn, words, n1) for k, fn in paths.items()}
        if all(v is not None for v in dt.values()):
            out["device_ms_per_pass"] = {k: round(v, 3) for k, v in dt.items()}
            out["device_ratio_rows"] = round(dt["single"] / dt["rows"], 4)
            out["device_ratio_2d"] = round(dt["single"] / dt["split2d"], 4)
            if rev >= 5:
                out["device_ratio_rows_vs_fast"] = round(
                    dt["single_fast"] / dt["rows"], 4)
                out["device_ratio_2d_vs_fast"] = round(
                    dt["single_fast"] / dt["split2d"], 4)
        else:
            out["device_ms_per_pass"] = None
    return out


def _fresh_sessions(args: list[str], sessions: int, label: str) -> list[dict]:
    """Run `sessions` fresh-process invocations of this tool, one JSON line
    each — the r4 protocol's answer to minute-scale tunnel drift."""
    results = []
    for i in range(sessions):
        log(f"{label} session {i + 1}/{sessions}")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *args],
            capture_output=True, text=True, cwd=REPO, timeout=3600,
        )
        if proc.returncode != 0:
            log(f"  session failed: {proc.stderr[-800:]}")
            continue
        results.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    if not results:
        raise SystemExit("no session succeeded")
    return results


def compare(size: int, rev: int = 5, sessions: int = 5) -> None:
    """Publish medians + full series across fresh-process sessions."""
    results = _fresh_sessions(
        ["--rev", str(rev), "session", str(size)], sessions, f"compare {size}"
    )
    for r in results:
        log(f"  ratios: rows={r['ratio_rows']} 2d={r['ratio_2d']}")
    ratios_rows = sorted(r["ratio_rows"] for r in results)
    ratios_2d = sorted(r["ratio_2d"] for r in results)
    _write(
        f"compare_{size}_r{rev}.json",
        {
            "protocol": "interleaved chained marginals; median across "
                        "fresh-process sessions (see benchmarks/README.md, "
                        "r4 protocol)",
            "size": size,
            "sessions": results,
            "runs_rows_ratio": ratios_rows,
            "runs_2d_ratio": ratios_2d,
            "rows_ratio_median": median(ratios_rows),
            "2d_ratio_median": median(ratios_2d),
        },
    )


def podshard_session() -> dict:
    """BASELINE config 5's per-chip shard both ways, one interleaved session:
    16x1 rows-only -> a (4096, 65536) shard; 4x4 2D -> a (16384, 16384)
    shard. Plus the single-chip temporal rate on the SAME (4096, 65536)
    array as the shared denominator."""
    import jax
    import jax.numpy as jnp

    from gol_tpu.ops import stencil_packed as sp
    from gol_tpu.parallel.mesh import PROXY_2D, SINGLE_DEVICE

    assert jax.default_backend() == "tpu"
    T = sp.TEMPORAL_GENS
    shard_16x1 = jnp.asarray(_host_words(4096, 65536))
    shard_4x4 = jnp.asarray(_host_words(16384, 16384, seed=43))

    def chain(step):
        def fn(w, n):
            return jax.lax.fori_loop(0, n, lambda i, x: step(x), w)
        return jax.jit(fn, static_argnums=1)

    runs = {
        "single_ref": (chain(lambda w: sp._step_t(w)[0]), shard_16x1),
        "rows_16x1": (
            chain(lambda w: sp._distributed_step_multi(w, SINGLE_DEVICE)[0]),
            shard_16x1,
        ),
        "split2d_4x4": (
            chain(lambda w: sp._distributed_step_multi(w, PROXY_2D)[0]),
            shard_4x4,
        ),
    }
    n1, n2 = 25, 100
    for name, (fn, w) in runs.items():
        t0 = time.perf_counter()
        _force(fn(w, 2))
        log(f"  warm {name}: {time.perf_counter() - t0:.0f}s")
    for fn, w in runs.values():  # discard round (see session())
        _force(fn(w, n1))
    rates = {k: [] for k in runs}
    for rep in range(3):
        t1 = {k: None for k in runs}
        t2 = {k: None for k in runs}
        for k, (fn, w) in runs.items():
            t0 = time.perf_counter(); _force(fn(w, n1)); t1[k] = time.perf_counter() - t0
        for k, (fn, w) in runs.items():
            t0 = time.perf_counter(); _force(fn(w, n2)); t2[k] = time.perf_counter() - t0
        for k in runs:
            per_pass = (t2[k] - t1[k]) / (n2 - n1)
            cells = 4096 * 65536  # both shards are the same cell count
            rates[k].append(cells * T / per_pass)
        log(f"  rep {rep}: " + ", ".join(f"{k}={rates[k][-1]/1e12:.2f}T" for k in runs))
    med = {k: median(v) for k, v in rates.items()}
    return {
        "cells_per_s": {k: [round(x) for x in v] for k, v in rates.items()},
        "ratio_rows_16x1": round(med["rows_16x1"] / med["single_ref"], 4),
        "ratio_split2d_4x4": round(med["split2d_4x4"] / med["single_ref"], 4),
        "single_ref_cells_per_s": round(med["single_ref"]),
    }


def podshard(rev: int = 5, sessions: int = 5) -> None:
    results = _fresh_sessions(
        ["--rev", str(rev), "podshard-session"], sessions, "podshard"
    )
    for r in results:
        log(f"  ratios: 16x1={r['ratio_rows_16x1']} "
            f"4x4={r['ratio_split2d_4x4']}")
    r16 = sorted(r["ratio_rows_16x1"] for r in results)
    r44 = sorted(r["ratio_split2d_4x4"] for r in results)
    _write(
        f"configs_r{rev}.json",
        {
            "what": "BASELINE config 5 (65536^2 on 16 chips) per-chip shard, "
                    "both meshes, one chip with local wraps standing in for "
                    "ICI ppermutes; ratios vs the single-chip temporal rate "
                    "on the same cell count",
            "sessions": results,
            "ratio_16x1_runs": r16,
            "ratio_4x4_runs": r44,
            "ratio_16x1_median": median(r16),
            "ratio_4x4_median": median(r44),
        },
    )


# ---------------------------------------------------------------------------
# Termination-block A/B (formerly measure_block_r5.py): now via the engine's
# per-runner plan parameter, so every variant is a first-class build.
# ---------------------------------------------------------------------------


def block_ab(size: int = 65536, gens: int = 1000,
             blocks: list[int] | None = None) -> None:
    blocks = blocks or [16, 64, 128]

    import jax
    import jax.numpy as jnp

    from gol_tpu import engine
    from gol_tpu.config import GameConfig
    from gol_tpu.tune.space import EnginePlan

    assert jax.default_backend() == "tpu", jax.default_backend()
    rng = np.random.default_rng(42)
    words = jnp.asarray(rng.integers(
        0, np.iinfo(np.uint32).max, size=(size, size // 32),
        dtype=np.uint32, endpoint=True,
    ))
    config = GameConfig(gen_limit=gens)

    runners = {}
    for b in blocks:
        t0 = time.perf_counter()
        # _build_runner directly with an explicit plan: the lru_cached
        # factories key on (shape, config, mesh, kernel), not the block.
        r = engine._build_runner(
            (size, size), config, None, "packed",
            segmented=False, packed_state=True,
            plan=EnginePlan(termination_block=b),
        )
        out = r(words)
        g = int(out[1])  # scalar readback = reliable completion barrier
        log(f"  block {b}: compile+first run {time.perf_counter() - t0:.0f}s, "
            f"{g} generations")
        runners[b] = r

    reps = 4
    times = {b: [] for b in blocks}
    for rep in range(reps):
        for b in blocks:  # interleaved round-robin
            t0 = time.perf_counter()
            out = runners[b](words)
            int(out[1])
            times[b].append(time.perf_counter() - t0)
            log(f"  rep {rep} block {b}: {times[b][-1]:.2f}s")
    best = {b: min(v) for b, v in times.items()}
    rates = {b: size * size * gens / best[b] for b in blocks}
    payload = {
        "what": "termination-block A/B on the headline packed-state run via "
                "the engine's plan parameter; interleaved repeats in one "
                "process, best-of wall",
        "size": size,
        "gen_limit": gens,
        "wall_s": {str(b): [round(t, 3) for t in v] for b, v in times.items()},
        "cells_per_s_best": {str(b): round(r) for b, r in rates.items()},
        "ratio_vs_first": {
            str(b): round(rates[b] / rates[blocks[0]], 4) for b in blocks
        },
    }
    _write("block_ab_r5.json", payload)
    print(json.dumps(payload["cells_per_s_best"]))


# ---------------------------------------------------------------------------
# r3 one-off probes (codec/transfer decomposition, config5 end-to-end).
# ---------------------------------------------------------------------------


def compare32k(size: int = 32768, g1: int = 200, repeats: int = 5) -> None:
    """r3 single-process A/B: kept for artifact reproducibility; the r4/r5
    ``compare`` protocol (fresh-process medians) supersedes it."""
    import jax
    import jax.numpy as jnp

    from gol_tpu.ops import stencil_packed as sp
    from gol_tpu.parallel.mesh import PROXY_2D, SINGLE_DEVICE

    words = jnp.asarray(_host_words(size, size))
    words.block_until_ready()
    log("words on device")

    def loop(step, calls):
        def run(state):
            final = jax.lax.fori_loop(0, calls, lambda i, s: step(s), state)
            return final[0, 0]

        return jax.jit(run)

    paths = {
        "packed-temporal-T8": lambda w: sp._step_t(w)[0],
        "packed-dist-temporal": lambda w: sp._distributed_step_multi(
            w, SINGLE_DEVICE
        )[0],
        "packed-dist-temporal-2d": lambda w: sp._distributed_step_multi(
            w, PROXY_2D
        )[0],
    }
    g2 = 3 * g1
    runs, best = {}, {}
    for name, step in paths.items():
        for gens in (g1, g2):
            run = loop(step, gens // sp.TEMPORAL_GENS)
            int(run(words))
            log("compiled", name, gens)
            runs[name, gens] = run
            best[name, gens] = float("inf")
    for rep in range(repeats):
        for key, run in runs.items():
            t0 = time.perf_counter()
            int(run(words))
            best[key] = min(best[key], time.perf_counter() - t0)
        log(f"rep {rep + 1}/{repeats} done")
    res = {}
    for name in paths:
        marg = (best[name, g2] - best[name, g1]) / (g2 - g1)
        res[name] = size * size / marg
        log(f"{name:26s} {marg * 1e3:8.3f} ms/gen  {res[name]:.3e} cells/s")
    ratio = res["packed-dist-temporal"] / res["packed-temporal-T8"]
    ratio_2d = res["packed-dist-temporal-2d"] / res["packed-temporal-T8"]
    _write(
        f"compare_{size}_r3.json",
        {
            "metric": "dist_temporal_vs_single_chip",
            "value": ratio,
            "unit": "ratio",
            "vs_baseline": None,
            "detail": res,
            "ratio_2d_form": ratio_2d,
            "size": size,
            "generations": [g1, g2],
            "note": (
                "marginal rates, fixed-count fori_loop, one chip, repeats "
                "interleaved across paths to cancel the tunnel chip's "
                "minute-scale drift; superseded by the r4/r5 fresh-process "
                "median protocol (tools/measure.py compare)."
            ),
        },
    )


def h2d(size: int = 65536) -> None:
    """Read-phase decomposition: codec pack throughput (text bytes -> packed
    words, host-only) and host->device upload throughput, measured apart so
    the config5 Reading-file number has a written breakdown — which side is
    the bound, storage/codec or the attach tunnel."""
    import jax

    from gol_tpu import native
    from gol_tpu.io.text_grid import row_stride

    rng = np.random.default_rng(7)
    rows = 8192  # 8192 x 65537 text bytes ~ 512MB sample of the 4.3GB file
    text = rng.integers(ord("0"), ord("2"), size=(rows, row_stride(size)),
                        dtype=np.uint8)
    text[:, -1] = ord("\n")
    t0 = time.perf_counter()
    packed = native.pack_text(text, size)
    pack_s = time.perf_counter() - t0
    text_mb = text.nbytes / (1 << 20)

    words = rng.integers(0, 2**32, size=(size, size // 32), dtype=np.uint32)
    t0 = time.perf_counter()
    jax.device_put(words).block_until_ready()
    # block_until_ready can return early over the tunnel; settle with a
    # tiny readback tied to the uploaded buffer.
    up = jax.device_put(words)
    int(up[0, 0])
    h2d_s = (time.perf_counter() - t0) / 2  # two uploads timed
    mb = words.nbytes / (1 << 20)
    _write(
        "h2d_probe_r3.json",
        {
            "metric": "h2d_throughput",
            "value": mb / h2d_s,
            "unit": "MB/s",
            "vs_baseline": None,
            "detail": {
                "pack_text_MBps": round(text_mb / pack_s, 1),
                "pack_sample_bytes": text.nbytes,
                "h2d_s_per_512MB": round(h2d_s, 3),
            },
            "bytes": words.nbytes,
            "note": "codec pack rate is per-thread (read_packed fans it "
            "over a pool); upload is one 512MB device_put over the attach "
            "tunnel — together they bound the packed read phase.",
        },
    )


def d2h(size: int = 65536) -> None:
    """Device->host throughput probes for the write phase: one-shot vs
    chunked at prefetch depths 1, 2 and 4 (the packed_io pipeline's knob)."""
    import jax.numpy as jnp

    from gol_tpu.io import packed_io

    nwords = size // 32
    rng = np.random.default_rng(1)
    host = rng.integers(0, 2**32, size=(size, nwords), dtype=np.uint32)
    words = jnp.asarray(host)
    words.block_until_ready()
    log("words on device:", host.nbytes >> 20, "MB")
    results = {}

    t0 = time.perf_counter()
    np.asarray(words)
    results["oneshot_s"] = time.perf_counter() - t0

    chunk_rows = max(1, packed_io._WRITE_CHUNK_BYTES // (nwords * 4))
    for depth in (1, 2, 4):
        import concurrent.futures

        starts = list(range(0, size, chunk_rows))
        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(max_workers=depth) as pool:
            blocks = list(
                pool.map(
                    lambda s: np.ascontiguousarray(words[s : s + chunk_rows]),
                    starts,
                )
            )
        results[f"chunked_depth{depth}_s"] = time.perf_counter() - t0
        del blocks
    mb = host.nbytes / (1 << 20)
    _write(
        "d2h_probe_r3.json",
        {
            "metric": "d2h_throughput",
            "value": mb / results["oneshot_s"],
            "unit": "MB/s",
            "vs_baseline": None,
            "detail": {k: round(v, 3) for k, v in results.items()},
            "bytes": host.nbytes,
            "note": "device->host transfer probes over the attach tunnel; "
            "chunked figures include the per-chunk device slice dispatch.",
        },
    )


def config5(size: int = 65536, gens: int = 10000) -> None:
    """The north-star workload end-to-end through the CLI, phases recorded."""
    import re
    import tempfile

    td = tempfile.mkdtemp(prefix="gol_config5_")
    inp = os.path.join(td, "input.txt")
    env = dict(os.environ)
    # The package is not installed; prepend (don't clobber — it carries the
    # TPU backend registration) the repo onto PYTHONPATH.
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    log("generating", size, "input at", inp)
    subprocess.run(
        [sys.executable, "-m", "gol_tpu", "generate", str(size), str(size),
         "--seed", "5", "--output", inp],
        check=True, cwd=REPO, env=env,
    )
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "gol_tpu", str(size), str(size), inp,
         "--variant", "tpu", "--packed-io", "--warmup",
         "--gen-limit", str(gens)],
        capture_output=True, text=True, check=True, cwd=td, env=env,
    )
    wall = time.perf_counter() - t0
    log(proc.stdout)
    phases = dict(
        re.findall(r"(Reading file|Execution time|Writing file):\t([0-9.]+)",
                   proc.stdout)
    )
    generations = int(re.search(r"Generations:\t(\d+)", proc.stdout).group(1))
    exec_s = float(phases["Execution time"]) / 1000
    rate = size * size * generations / exec_s
    _write(
        "config5_r3.json",
        {
            "metric": "cell_updates_per_sec_per_chip",
            "value": rate,
            "unit": "cells/s",
            "vs_baseline": rate / 1e11,
            "phases_ms": {k: float(v) for k, v in phases.items()},
            "generations": generations,
            "wall_s": round(wall, 1),
            "size": size,
            "note": "BASELINE.md config 5 end-to-end via the CLI on one "
            "chip: packed I/O + temporal kernel + chunked D2H write "
            "pipeline at depth GOL_D2H_DEPTH (default 2). Read/write "
            "phases ride the attach tunnel, whose throughput drifts "
            "several-x between sessions; Execution time is on-device and "
            "comparable across sessions.",
        },
    )


_R3_STEPS = {"compare32k": compare32k, "h2d": h2d, "d2h": d2h,
             "config5": config5}

# The historical per-round entry points (measure_r3.py .. measure_block_r5.py)
# map onto this tool's argv here, in ONE table — the shims themselves carry
# no argument plumbing anymore, just `shim_main(__file__)`.
_SHIM_ARGS = {
    "measure_r3": ["--rev", "3"],
    "measure_r4": ["--rev", "4"],
    "measure_r5": ["--rev", "5"],
    "measure_block_r5": ["block"],
}


def shim_main(shim_path: str, argv: list[str] | None = None) -> int:
    """Entry point for the legacy shim filenames: prepend the shim's
    recorded arguments (the ``--rev`` / subcommand it historically pinned)
    and run ``main``."""
    name = os.path.splitext(os.path.basename(shim_path))[0]
    prepend = _SHIM_ARGS[name]
    return main([*prepend, *(sys.argv[1:] if argv is None else list(argv))])


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    rev = 5
    if argv[:1] == ["--rev"]:
        if len(argv) < 2:
            raise SystemExit("--rev needs a value (3, 4 or 5)")
        rev = int(argv[1])
        argv = argv[2:]
    if rev not in (3, 4, 5):
        raise SystemExit(f"unknown protocol rev {rev}; one of 3, 4, 5")
    cmd = argv[0] if argv else "all"
    rest = argv[1:]

    if cmd == "block":
        block_ab(
            int(rest[0]) if len(rest) > 0 else 65536,
            int(rest[1]) if len(rest) > 1 else 1000,
            [int(b) for b in rest[2:]] or None,
        )
        return 0
    if rev == 3:
        names = list(_R3_STEPS) if cmd == "all" else [cmd]
        for name in names:
            if name not in _R3_STEPS:
                raise SystemExit(
                    f"unknown r3 step {name}; one of {sorted(_R3_STEPS)} or block"
                )
            log("=== step:", name)
            _R3_STEPS[name]()
        return 0
    if cmd == "session":
        print(json.dumps(session(int(rest[0]), rev=rev)))
    elif cmd == "podshard-session":
        print(json.dumps(podshard_session()))
    elif cmd == "compare":
        compare(int(rest[0]), rev, int(rest[1]) if len(rest) > 1 else 5)
    elif cmd == "podshard":
        podshard(rev, int(rest[0]) if len(rest) > 0 else 5)
    elif cmd == "all":
        compare(16384, rev)
        compare(32768, rev)
        podshard(rev)
    else:
        raise SystemExit(f"unknown subcommand {cmd}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
