"""Sparse-engine smoke: tile-boundary byte-gate + SIGKILL auto-resume.

The `make sparse-smoke` harness, exercising ISSUE 12's two end-to-end
acceptance behaviors against real processes:

1. **Glider flight across tile boundaries** — a glider crosses >= 4 tile
   boundaries (64x64 universe, 8^2 tiles, 300 generations with toroidal
   wrap) and the sparse lane's final universe is byte-checked against the
   dense engine AND the NumPy oracle, for BOTH conventions, with the tile
   memo on (the production configuration).

2. **SIGKILL mid-run -> auto-resume identical** — a real `gol serve`
   process takes a long sparse job (journaled as its RLE spec), is
   SIGKILLed before the job completes, and a restart on the same journal
   replays the spec — the occupancy index is rebuilt from it — and
   re-runs to a result byte-identical to an uninterrupted reference
   server's, with exactly one done record in the journal.

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/sparse_smoke.py
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GLIDER_RLE = "x = 3, y = 3, rule = B3/S23\nbob$2bo$3o!"


def fail(msg: str) -> None:
    print(f"SPARSE-SMOKE FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def check_tile_boundaries() -> None:
    """Glider across >= 4 tile boundaries, byte-gated vs dense + oracle."""
    from gol_tpu import engine, oracle
    from gol_tpu.config import GameConfig
    from gol_tpu.io import rle
    from gol_tpu.sparse import SparseBoard, TileMemo, simulate_sparse

    glider = rle.parse(GLIDER_RLE)
    for convention in ("c", "cuda"):
        cfg = GameConfig(gen_limit=300, convention=convention)
        dense = np.zeros((64, 64), np.uint8)
        dense[1:4, 1:4] = glider
        ref = oracle.run(dense.copy(), cfg)
        eng = engine.simulate(dense.copy(), cfg)
        if not np.array_equal(ref.grid, eng.grid) \
                or ref.generations != eng.generations:
            fail(f"dense engine disagrees with oracle ({convention})")
        board = SparseBoard.from_dense(dense, tile=8)
        result = simulate_sparse(board, cfg, TileMemo())
        if result.generations != ref.generations:
            fail(
                f"sparse generations {result.generations} != "
                f"{ref.generations} ({convention})"
            )
        if not np.array_equal(result.board.to_dense(), ref.grid):
            fail(f"sparse cells differ from dense ({convention})")
        # 300 generations moves the glider ~75 cells diagonally (with
        # wrap): many 8-cell tile boundaries crossed, corners included.
        print(
            f"  boundary gate ({convention}): {result.generations} gens, "
            f"{result.stats.tiles_active} tile-steps, byte-identical",
            file=sys.stderr,
        )


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(method, url, body=None, timeout=10):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _start_server(port: int, journal_dir: str) -> subprocess.Popen:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "gol_tpu", "serve",
            "--port", str(port),
            "--journal-dir", journal_dir,
            "--flush-age", "0.02",
        ],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    base = f"http://127.0.0.1:{port}"
    deadline = time.perf_counter() + 120
    while time.perf_counter() < deadline:
        if proc.poll() is not None:
            fail(f"server died at boot:\n{proc.stdout.read()}")
        try:
            _http("GET", base + "/metrics?format=json", timeout=2)
            return proc
        except (urllib.error.URLError, OSError):
            time.sleep(0.1)
    fail("server did not become ready")


SPARSE_JOB = {
    "width": 512, "height": 512, "rle": GLIDER_RLE,
    "x": 40, "y": 80, "tile": 64, "gen_limit": 600,
}


def _submit(base: str) -> str:
    status, out = _http("POST", base + "/jobs", SPARSE_JOB)
    if status != 202:
        fail(f"submit answered {status}")
    return out["id"]


def _await_done(base: str, job_id: str, timeout=300) -> dict:
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        try:
            status, out = _http("GET", f"{base}/result/{job_id}")
        except urllib.error.HTTPError as e:
            if e.code in (409, 503):
                time.sleep(0.2)
                continue
            raise
        if status == 200:
            return out
        time.sleep(0.2)
    fail(f"job {job_id} did not finish in {timeout}s")


def check_sigkill_resume() -> None:
    """SIGKILL mid-sparse-run; restart replays the RLE spec to an
    identical result (the occupancy-index replay path)."""
    workdir = tempfile.mkdtemp(prefix="sparse-smoke-")
    try:
        # Reference: an uninterrupted server runs the same job to DONE.
        ref_journal = os.path.join(workdir, "ref-journal")
        port = _free_port()
        proc = _start_server(port, ref_journal)
        base = f"http://127.0.0.1:{port}"
        ref = _await_done(base, _submit(base))
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)

        # Victim: submit, SIGKILL while the job is (very likely) running,
        # restart on the same journal, expect replay to re-run it.
        journal = os.path.join(workdir, "journal")
        port = _free_port()
        proc = _start_server(port, journal)
        base = f"http://127.0.0.1:{port}"
        job_id = _submit(base)
        time.sleep(0.6)  # let the worker claim the job mid-run
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)

        port = _free_port()
        proc = _start_server(port, journal)
        base = f"http://127.0.0.1:{port}"
        out = _await_done(base, job_id)
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)

        for key in ("rle", "generations", "exit_reason", "population"):
            if out.get(key) != ref.get(key):
                fail(
                    f"post-SIGKILL result differs on {key!r}: "
                    f"{str(out.get(key))[:80]} != {str(ref.get(key))[:80]}"
                )
        # Exactly one done record for the id across the whole journal
        # (compaction.iter_records: snapshot + sealed segments + live
        # file, so the audit survives rotation/compaction).
        from gol_tpu.serve import compaction

        done = 0
        for rec in compaction.iter_records(journal):
            if rec.get("event") == "done" and rec.get("id") == job_id:
                done += 1
        if done != 1:
            fail(f"{done} done records for {job_id} (want exactly 1)")
        print(
            f"  SIGKILL gate: replayed job {job_id[:8]} re-ran to an "
            f"identical result (gens {out['generations']}, "
            f"population {out['population']}, 1 done record)",
            file=sys.stderr,
        )
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def main() -> int:
    check_tile_boundaries()
    check_sigkill_resume()
    print("SPARSE-SMOKE PASS", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
