"""Chaos smoke: a real fleet under seeded network faults + a SIGKILL.

The `make chaos-smoke` harness — the PR-14 acceptance run against real OS
processes, with the chaos proxy armed on the router->worker data path:

1. boot ``gol fleet --workers 2 --chaos PLAN`` (seeded plan mixing
   connection resets, added latency, and GOLP frame corruption) with
   breakers on (the CLI default), a 1s breaker cooldown, and a retry
   budget on the workers' dispatch path;
2. submit N jobs as PACKED wire frames through the router (the CRC-gated
   lane: a frame the chaos hop corrupts is caught, never run wrong),
   tolerating the documented fault contracts — ambiguous 504s (resubmit
   knowingly), CRC 400s (re-send; no job was created), and corrupted 202
   bodies (an id that never answers is a torn response, not a lost job);
3. SIGKILL one worker that accepted work MID-LOAD, then keep submitting:
   the router's forwards to the dead worker must trip its breaker OPEN
   (observed via /fleet), the health loop respawns the worker on the same
   partition, and a half-open probe must re-CLOSE the breaker;
4. wait until every accepted job reports DONE through the router (the
   victim's partition replays; chaos keeps injecting the whole time);
5. fetch a sample of results as packed frames (CRC re-verified client
   side) and compare byte-identically against the NumPy oracle;
6. SIGTERM the fleet (graceful cascade, rc 0), then audit:
   - every accepted id holds EXACTLY one done record across both
     partition journals (none lost, none double-run);
   - the durable breaker ring (``<fleet-dir>/routers/r0/breaker-history``,
     the primary router's per-replica state dir) recorded the victim's
     open AND the re-close — the decision trail an operator replays
     after the fact.

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/chaos_smoke.py [--jobs 60] [--gen-limit 200]
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from gol_tpu import oracle  # noqa: E402
from gol_tpu.config import GameConfig  # noqa: E402
from gol_tpu.fleet import client as fleet_client  # noqa: E402
from gol_tpu.io import text_grid, wire  # noqa: E402
from gol_tpu.obs import history as obs_history  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Seeded mix: resets (the ambiguous class), latency (the breaker's
# slow-call signal), and frame corruption (the CRC gate's class) — every
# leg of the defense exercised at once, deterministically.
CHAOS_PLAN = "seed=42,reset=0.02,latency=0.15,latency_ms=25,bitflip=0.02"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(method, url, body=None, timeout=30):
    try:
        return fleet_client.http_json(method, url, body, timeout=timeout)
    except ConnectionError as e:
        # Normalized torn-HTTP (fleet/client.py): callers here treat it
        # like any other connection trouble.
        raise urllib.error.URLError(str(e)) from e


def _start_fleet(port: int, fleet_dir: str):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "gol_tpu", "fleet",
            "--port", str(port),
            "--workers", "2",
            "--fleet-dir", fleet_dir,
            "--flush-age", "0.05",
            # A wide-ish tick: the supervisor SEES direct probes only, so
            # the window between a kill and its detection is where the
            # BREAKER (which sees the data path) must carry the defense —
            # exactly the brownout shape health checks miss.
            "--health-interval", "2.0",
            "--chaos", CHAOS_PLAN,
            "--breaker-cooldown", "1.0",
            "--retry-budget", "50",
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    deadline = time.perf_counter() + 300
    base = f"http://127.0.0.1:{port}"
    while time.perf_counter() < deadline:
        if proc.poll() is not None:
            out, _ = proc.communicate()
            raise RuntimeError(
                f"fleet died on boot rc={proc.returncode}:\n{out[-4000:]}"
            )
        try:
            status, payload = _http("GET", f"{base}/healthz", timeout=2)
            if status == 200 and payload.get("fleet", {}).get("workers") == 2:
                return proc
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.2)
    proc.kill()
    raise RuntimeError("fleet did not become healthy within 300s")


def _fleet_json(base: str) -> dict:
    status, payload = _http("GET", f"{base}/fleet")
    if status != 200 or not isinstance(payload, dict):
        raise RuntimeError(f"GET /fleet -> {status}: {payload}")
    return payload


def _job_state(base: str, job_id: str):
    """The job's state, or None for 'ask again' (transient 5xx, a
    bit-flipped poll body, a respawn window)."""
    try:
        status, payload = _http("GET", f"{base}/jobs/{job_id}", timeout=10)
    except (urllib.error.URLError, OSError):
        return None
    if status == 404:
        return "unknown"
    if status != 200 or not isinstance(payload, dict):
        return None
    return payload.get("state")


def _id_answers(base: str, job_id: str, tries: int = 20) -> bool:
    """A 202 body the chaos hop corrupted carries a garbled id: the job
    exists under its TRUE id on the worker, but THIS id 404s forever —
    detect it so the submit loop can resubmit knowingly."""
    for _ in range(tries):
        state = _job_state(base, job_id)
        if state == "unknown":
            return False
        if state:
            return True
        time.sleep(0.05)
    return False


def _submit_packed(base: str, board, gen_limit: int, anomalies: dict):
    """One board -> one ACCEPTED, answering job id, riding out every
    documented fault contract on the way."""
    frame = wire.encode_frame({"gen_limit": gen_limit}, grid=board)
    for _ in range(80):
        try:
            status, payload = fleet_client.http_json(
                "POST", f"{base}/jobs", raw=frame,
                content_type=wire.CONTENT_TYPE, timeout=30,
            )
        except (urllib.error.URLError, ConnectionError, OSError):
            anomalies["transport"] = anomalies.get("transport", 0) + 1
            time.sleep(0.1)
            continue
        if status == 202 and isinstance(payload, dict):
            job_id = payload.get("id")
            if job_id and _id_answers(base, job_id):
                return job_id
            anomalies["garbled_202"] = anomalies.get("garbled_202", 0) + 1
            time.sleep(0.1)
            continue
        if status == 504:
            # Ambiguous outcome: the body names the worker whose outcome
            # is unknown; resubmit knowingly (fresh id).
            who = payload.get("worker") if isinstance(payload, dict) else None
            anomalies.setdefault("ambiguous_504", []).append(who)
            time.sleep(0.1)
            continue
        if status in (400, 503, 429):
            # 400: the CRC gate caught a flipped frame (no job created);
            # 503/429: momentary spill/shed exhaustion. All re-send safe.
            anomalies[f"http_{status}"] = anomalies.get(f"http_{status}",
                                                        0) + 1
            time.sleep(0.1)
            continue
        raise RuntimeError(f"unexpected submit answer {status}: {payload}")
    raise RuntimeError("a submit never landed after 80 tries")


def _fetch_result_packed(base: str, job_id: str, tries: int = 80):
    """(meta, grid) through the chaos hop: WireError = corrupted in
    transit -> refetch (the frame on the worker is intact)."""
    for _ in range(tries):
        try:
            status, ctype, body = fleet_client.http_exchange(
                "GET", f"{base}/result/{job_id}",
                headers={"Accept": wire.CONTENT_TYPE}, timeout=30,
            )
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.1)
            continue
        if status >= 500:
            time.sleep(0.1)
            continue
        if status != 200:
            raise RuntimeError(f"result {job_id} HTTP {status}")
        if not wire.is_packed(ctype):
            raise RuntimeError(f"result {job_id} not packed ({ctype})")
        try:
            frame = wire.decode_frame(body)
        except wire.WireError:
            time.sleep(0.05)
            continue
        return dict(frame.meta), frame.grid()
    raise RuntimeError(f"result {job_id} never fetched clean")


def _count_done(fleet_dir: str) -> dict:
    # compaction.iter_records (snapshot + sealed segments + live file):
    # the audit survives journal rotation/compaction on busy partitions.
    from gol_tpu.serve import compaction

    done: dict = {}
    for name in sorted(os.listdir(fleet_dir)):
        part = os.path.join(fleet_dir, name)
        if not os.path.isfile(os.path.join(part, "journal.jsonl")):
            continue
        for rec in compaction.iter_records(part):
            if rec.get("event") == "done":
                done.setdefault(rec["id"], []).append(name)
    return done


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--jobs", type=int, default=60)
    parser.add_argument("--gen-limit", type=int, default=200)
    parser.add_argument("--sample", type=int, default=20,
                        help="results to oracle-verify (packed, CRC-gated)")
    args = parser.parse_args(argv)

    workdir = tempfile.mkdtemp(prefix="gol-chaos-smoke-")
    fleet_dir = os.path.join(workdir, "fleet")
    port = _free_port()
    base = f"http://127.0.0.1:{port}"
    cfg = GameConfig(gen_limit=args.gen_limit)
    sides = (32, 64)

    rc = 1
    proc = None
    try:
        proc = _start_fleet(port, fleet_dir)
        print(f"chaos-smoke: 2-worker fleet up on {base} with chaos ARMED "
              f"({CHAOS_PLAN})")

        anomalies: dict = {}
        accepted = {}  # id -> board
        boards = [text_grid.generate(sides[i % 2], sides[i % 2],
                                     seed=3000 + i)
                  for i in range(args.jobs)]
        half = args.jobs // 2
        for i in range(half):
            accepted[_submit_packed(base, boards[i], args.gen_limit,
                                    anomalies)] = boards[i]
        print(f"chaos-smoke: {half} jobs in through the faulty hop "
              f"(anomalies so far: {anomalies or 'none'})")

        # SIGKILL a worker that is holding work, mid-load.
        workers = _fleet_json(base)["workers"]
        victim = workers[0]
        print(f"chaos-smoke: SIGKILL worker {victim['id']} "
              f"(pid {victim['pid']}) mid-load")
        os.kill(victim["pid"], signal.SIGKILL)

        # A fast fire-and-forget burst at BOTH buckets: forwards that land
        # on the dead worker (its bucket still ranks it first — the health
        # tick has not flagged it yet) must trip its breaker OPEN. This is
        # the breaker's whole reason to exist: the DATA path notices the
        # failure attempts-faster than the supervisor's direct probe tick.
        burst = [text_grid.generate(s, s, seed=5000 + j)
                 for j, s in enumerate((32, 64, 32, 64))]
        saw_open = False
        deadline = time.perf_counter() + 120
        while time.perf_counter() < deadline and not saw_open:
            for b in burst:
                frame = wire.encode_frame({"gen_limit": 4}, grid=b)
                try:
                    fleet_client.http_json(
                        "POST", f"{base}/jobs", raw=frame,
                        content_type=wire.CONTENT_TYPE, timeout=10)
                except (urllib.error.URLError, ConnectionError, OSError):
                    pass  # the dead hop answering with an RST: expected
            try:
                states = _fleet_json(base).get("breakers") or {}
            except (RuntimeError, urllib.error.URLError, OSError):
                states = {}
            if states.get(victim["id"]) in ("open", "half-open"):
                saw_open = True
        if not saw_open:
            print("chaos-smoke: breaker never opened for the killed worker")
            return 1
        print(f"chaos-smoke: breaker OPEN observed for {victim['id']}")

        # Finish the load while the respawn + half-open probe re-close it.
        i = half
        while i < args.jobs:
            accepted[_submit_packed(base, boards[i], args.gen_limit,
                                    anomalies)] = boards[i]
            i += 1
        deadline = time.perf_counter() + 300
        while time.perf_counter() < deadline:
            try:
                states = _fleet_json(base).get("breakers") or {}
            except (RuntimeError, urllib.error.URLError, OSError):
                states = {}
            if states.get(victim["id"]) == "closed":
                break
            # A trickle of probes across BOTH buckets (the victim owns
            # only one of them): ranked attempts are what half-open turns
            # into recovery.
            for b in burst[:2]:
                _submit_packed(base, b, 4, anomalies)
            time.sleep(0.25)
        else:
            print("chaos-smoke: breaker never re-closed after the respawn")
            return 1
        print(f"chaos-smoke: breaker re-CLOSED for {victim['id']} "
              f"after respawn")

        # Every accepted job -> DONE, through replay + injected faults.
        deadline = time.perf_counter() + 600
        pending = set(accepted)
        while pending and time.perf_counter() < deadline:
            for job_id in list(pending):
                state = _job_state(base, job_id)
                if state == "done":
                    pending.discard(job_id)
                elif state in ("failed", "cancelled", "unknown"):
                    print(f"chaos-smoke: job {job_id} ended {state}")
                    return 1
            if pending:
                time.sleep(0.2)
        if pending:
            print(f"chaos-smoke: {len(pending)} job(s) never completed")
            return 1
        print(f"chaos-smoke: all {len(accepted)} accepted jobs DONE "
              f"(anomalies ridden out: {anomalies or 'none'})")

        # Sampled results: packed fetch, client-side CRC, oracle-identical.
        sample = list(accepted.items())[:: max(
            1, len(accepted) // max(1, args.sample))][:args.sample]
        for job_id, board in sample:
            meta, got = _fetch_result_packed(base, job_id)
            want = oracle.run(board, cfg)
            if (not np.array_equal(np.asarray(got), want.grid)
                    or meta.get("generations") != want.generations):
                print(f"chaos-smoke: result {job_id} diverges from oracle")
                return 1
        print(f"chaos-smoke: {len(sample)} sampled results "
              "oracle-identical through the faulty hop")

        # Graceful cascade out.
        proc.send_signal(signal.SIGTERM)
        try:
            out, _ = proc.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            print("chaos-smoke: fleet ignored SIGTERM")
            proc.kill()
            return 1
        if proc.returncode != 0:
            print(f"chaos-smoke: fleet exited rc={proc.returncode}:\n"
                  f"{out[-3000:]}")
            return 1
        proc = None

        # The durable breaker ring recorded the open AND the re-close.
        ring_dir = os.path.join(fleet_dir, "routers", "r0",
                                "breaker-history")
        transitions = [r["breaker"] for r
                       in obs_history.read_records(ring_dir)
                       if "breaker" in r and "record_kind" not in r]
        opens = [t for t in transitions if t.get("to") == "open"]
        closes = [t for t in transitions if t.get("to") == "closed"]
        if not opens or not closes:
            print(f"chaos-smoke: breaker ring incomplete: {transitions}")
            return 1
        print(f"chaos-smoke: breaker ring recorded {len(opens)} open / "
              f"{len(closes)} close transition(s)")

        # Fleet-wide exactly-once for every accepted id.
        done = _count_done(fleet_dir)
        lost = set(accepted) - set(done)
        dup = {k: v for k, v in done.items()
               if k in accepted and len(v) != 1}
        if lost or dup:
            print(f"chaos-smoke: lost={lost} duplicated={dup}")
            return 1
        print(
            f"chaos-smoke: PASS — {len(accepted)} jobs exactly-once under "
            f"{CHAOS_PLAN} + SIGKILL; breakers opened and re-closed in the "
            "decision ring; sampled results oracle-identical"
        )
        rc = 0
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.communicate()
        if rc == 0:
            shutil.rmtree(workdir, ignore_errors=True)
        else:
            print(f"chaos-smoke: artifacts kept in {workdir}")


if __name__ == "__main__":
    sys.exit(main())
