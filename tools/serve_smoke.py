"""Serving crash/restart smoke: kill `gol serve` mid-batch, replay, verify.

The `make serve-smoke` harness, exercising the restart-safety acceptance
end-to-end against real OS processes:

1. boot `gol serve` on a free port with a fresh journal directory;
2. submit N jobs (default 50) across TWO bucket shapes (32x32 exact-fit
   packed and 30x30 masked) — every accepted id is remembered;
3. SIGKILL the server while work is in flight (mid-compile/mid-batch);
4. restart on the same journal: replay must re-queue exactly the accepted
   jobs with no terminal record;
5. wait until every accepted job reports DONE, then POST /drain and
   SIGTERM (the graceful path);
6. verify from the journal that every accepted id has EXACTLY one done
   record (none lost, none double-completed) and that every result is
   byte-identical to the NumPy oracle.

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/serve_smoke.py [--jobs 50] [--gen-limit 400]
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from gol_tpu import oracle  # noqa: E402
from gol_tpu.config import GameConfig  # noqa: E402
from gol_tpu.io import text_grid  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(method, url, body=None, timeout=10):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _start_server(port: int, journal_dir: str) -> subprocess.Popen:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "gol_tpu", "serve",
            "--port", str(port),
            "--journal-dir", journal_dir,
            "--flush-age", "0.05",
        ],
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    deadline = time.perf_counter() + 120
    base = f"http://127.0.0.1:{port}"
    while time.perf_counter() < deadline:
        if proc.poll() is not None:
            out, _ = proc.communicate()
            raise RuntimeError(f"server died on boot rc={proc.returncode}:\n{out[-3000:]}")
        try:
            status, _ = _http("GET", f"{base}/healthz", timeout=2)
            if status == 200:
                return proc
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.1)
    proc.kill()
    raise RuntimeError("server did not become healthy within 120s")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--jobs", type=int, default=50)
    parser.add_argument("--gen-limit", type=int, default=400)
    parser.add_argument(
        "--kill-after", type=float, default=0.8,
        help="seconds after the last submit to SIGKILL the first server",
    )
    args = parser.parse_args(argv)

    workdir = tempfile.mkdtemp(prefix="gol-serve-smoke-")
    journal_dir = os.path.join(workdir, "journal")
    port = _free_port()
    base = f"http://127.0.0.1:{port}"
    cfg = GameConfig(gen_limit=args.gen_limit)

    # Two bucket shapes: exact-fit packed (32x32) and padded masked (30x30).
    boards = {}
    rc = 1
    proc = None
    try:
        proc = _start_server(port, journal_dir)
        print(f"serve-smoke: server up on {base}, journal {journal_dir}")
        accepted = {}
        for i in range(args.jobs):
            side = 32 if i % 2 == 0 else 30
            board = text_grid.generate(side, side, seed=1000 + i)
            status, payload = _http("POST", f"{base}/jobs", {
                "width": side, "height": side,
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": args.gen_limit,
            })
            if status != 202:
                print(f"serve-smoke: submit {i} rejected HTTP {status}: {payload}")
                return 1
            accepted[payload["id"]] = board
            boards[payload["id"]] = board
        print(f"serve-smoke: accepted {len(accepted)} jobs across 2 buckets")

        # Kill mid-flight: the first dispatch of each bucket is still
        # compiling or running its first batches this soon after submit.
        time.sleep(args.kill_after)
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        proc = None

        done_before = _count_done(journal_dir)
        print(f"serve-smoke: SIGKILL'd server; journal shows "
              f"{len(done_before)} done of {len(accepted)}")

        # Restart on the same journal: replay finishes the remainder.
        proc = _start_server(port, journal_dir)
        deadline = time.perf_counter() + 600
        pending = set(accepted)
        while pending and time.perf_counter() < deadline:
            for job_id in list(pending):
                status, payload = _http("GET", f"{base}/jobs/{job_id}")
                if status != 200:
                    print(f"serve-smoke: job {job_id} LOST after restart "
                          f"(HTTP {status}: {payload})")
                    return 1
                state = payload["state"]
                if state == "done":
                    pending.discard(job_id)
                elif state in ("failed", "cancelled"):
                    print(f"serve-smoke: job {job_id} ended {state}: {payload}")
                    return 1
            if pending:
                time.sleep(0.2)
        if pending:
            print(f"serve-smoke: {len(pending)} job(s) never completed")
            return 1

        status, payload = _http("POST", f"{base}/drain", {}, timeout=60)
        if status != 200 or not payload.get("drained"):
            print(f"serve-smoke: drain failed HTTP {status}: {payload}")
            return 1
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            print("serve-smoke: server ignored SIGTERM")
            proc.kill()
            return 1
        proc = None

        # The exactly-once ledger: every accepted id -> exactly 1 done
        # record, and every recorded result matches the oracle.
        done = _count_done(journal_dir)
        lost = set(accepted) - set(done)
        extra = set(done) - set(accepted)
        dup = {k: v for k, v in done.items() if len(v) != 1}
        if lost or extra or dup:
            print(f"serve-smoke: lost={lost} unknown={extra} "
                  f"duplicated={{k: len(v) for k, v in dup.items()}}")
            return 1
        mismatches = 0
        for job_id, records in done.items():
            rec = records[0]
            want = oracle.run(accepted[job_id], cfg)
            got = text_grid.decode(
                rec["grid"].encode("ascii"), rec["width"], rec["height"]
            )
            if (
                not np.array_equal(np.asarray(got), want.grid)
                or rec["generations"] != want.generations
            ):
                mismatches += 1
        if mismatches:
            print(f"serve-smoke: {mismatches} result(s) diverge from the oracle")
            return 1
        print(
            f"serve-smoke: PASS — {len(accepted)} accepted, "
            f"{len(done_before)} done before the kill, remainder replayed; "
            f"every job done exactly once, all oracle-identical"
        )
        rc = 0
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.communicate()
        if rc == 0:
            shutil.rmtree(workdir, ignore_errors=True)
        else:
            print(f"serve-smoke: artifacts kept in {workdir}")


def _count_done(journal_dir: str) -> dict:
    """id -> [done records], enumerated via compaction.iter_records
    (snapshot + sealed segments + live file) so the audit survives
    journal rotation/compaction; torn tails tolerated as ever."""
    from gol_tpu.serve import compaction

    done: dict = {}
    if not os.path.exists(os.path.join(journal_dir, "journal.jsonl")):
        return done
    for rec in compaction.iter_records(journal_dir):
        if rec.get("event") == "done":
            done.setdefault(rec["id"], []).append(rec)
    return done


if __name__ == "__main__":
    sys.exit(main())
