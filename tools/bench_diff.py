"""Compare two BENCH_*.json artifacts with noise thresholds.

The repo accumulates one measurement artifact per perf PR (BENCH_r01..r09)
and, until now, no tooling to compare any two of them — a regression only
surfaced when a human eyeballed the JSON. This makes the comparison a
command with an exit code, so CI (or `make bench-diff`) can gate on it:

    python tools/bench_diff.py BENCH_old.json BENCH_new.json [--tolerance F]

- The **headline** ``value`` is judged directionally: metrics/units naming
  seconds/latency/time are lower-better, everything else (rates, speedup
  ratios, boards/s) higher-better. A move in the bad direction beyond
  ``--tolerance`` (relative, default 10% — comfortably outside the
  trimmed-median scatter the tune/ protocol sees on shared machines) exits
  nonzero.
- Every other shared numeric leaf is compared informationally: leaves that
  moved more than the tolerance are listed as drift (no exit-code verdict —
  nested fields mix directions and units; the headline is the contract).

With ``--history``, OLD and NEW are **metrics-history directories**
(``gol serve/fleet --metrics-history``, gol_tpu/obs/history.py) instead of
artifacts: the gated value is the whole-window rate of a cumulative
counter (``--metric``, default ``jobs_completed_total``) computed per
writer run and summed — respawn boundaries contribute their own deltas,
never a bogus negative one. An incident window gates against a baseline
window exactly like one bench run gates against another:

    python tools/bench_diff.py --history baseline/history incident/history

Exit codes: 0 within tolerance, 1 headline regression, 2 usage/shape error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Substrings marking a lower-is-better headline (times); everything else is
# treated as higher-is-better (rates, ratios, counts of useful work).
LOWER_BETTER_HINTS = ("seconds", "second", "latency", "_time", "msec", "ms")

# Nested leaves that are configuration, not measurement: never drift.
CONFIG_HINTS = ("seed", "iters", "gen_limit", "boards", "repeats",
                "max_batch", "ring", "checkpoint_every", "total_cell",
                "counts")


def flatten(doc, prefix: str = "") -> dict[str, float]:
    out: dict[str, float] = {}
    if isinstance(doc, dict):
        for key, value in doc.items():
            out.update(flatten(value, f"{prefix}{key}."))
    elif isinstance(doc, (int, float)) and not isinstance(doc, bool):
        out[prefix[:-1]] = float(doc)
    return out


def lower_is_better(metric: str, unit: str) -> bool:
    text = f"{metric} {unit}".lower()
    return any(h in text for h in LOWER_BETTER_HINTS)


def _is_config(path: str) -> bool:
    low = path.lower()
    return (low.startswith(("env.", "load.", "context."))
            or any(h in low for h in CONFIG_HINTS))


def compare(old: dict, new: dict, tolerance: float, metric: str | None = None):
    """(report lines, regressed?) for two parsed artifacts.

    ``metric`` selects a flattened nested leaf (dot path, e.g.
    ``lanes.fleet_n4.jobs_per_sec``) as the GATED value instead of the
    default headline ``value`` — for suites whose contract is a non-headline
    number (fleet CI gates on aggregate jobs/sec while the headline is a
    scaling ratio). Direction is inferred from the leaf path the same way
    it is from the metric name."""
    lines = []
    metric_old = old.get("metric", "?")
    metric_new = new.get("metric", "?")
    if metric_old != metric_new:
        raise ValueError(
            f"artifacts measure different things: {metric_old!r} vs "
            f"{metric_new!r} — compare runs of the SAME suite"
        )
    regressed = False
    if metric is not None:
        flat_old_g, flat_new_g = flatten(old), flatten(new)
        missing = [name for name, flat in
                   (("OLD", flat_old_g), ("NEW", flat_new_g))
                   if metric not in flat]
        if missing:
            raise ValueError(
                f"--metric {metric!r} is not a numeric leaf of the "
                f"{'/'.join(missing)} artifact(s); leaves look like "
                f"{sorted(flat_new_g)[:6]} ..."
            )
        v_old, v_new = flat_old_g[metric], flat_new_g[metric]
        unit = ""
        gated_name = metric
        lower = lower_is_better(metric, "")
    else:
        unit = str(new.get("unit", old.get("unit", "")))
        try:
            v_old, v_new = float(old["value"]), float(new["value"])
        except (KeyError, TypeError, ValueError):
            raise ValueError("both artifacts need a numeric headline 'value'")
        gated_name = str(metric_old)
        lower = lower_is_better(str(metric_old), unit)
    rel = (v_new - v_old) / abs(v_old) if v_old else 0.0
    bad = rel > tolerance if lower else rel < -tolerance
    better = rel < -tolerance if lower else rel > tolerance
    verdict = ("REGRESSION" if bad
               else "improvement" if better else "within tolerance")
    if bad:
        regressed = True
    lines.append(
        f"{'gated' if metric is not None else 'headline'} {gated_name} "
        f"({'lower' if lower else 'higher'} is "
        f"better): {v_old:g} -> {v_new:g} {unit} ({rel:+.1%}) — {verdict}"
    )

    flat_old, flat_new = flatten(old), flatten(new)
    shared = sorted(set(flat_old) & set(flat_new) - {"value", metric})
    drifted = []
    for path in shared:
        if _is_config(path):
            continue
        a, b = flat_old[path], flat_new[path]
        if a == b:
            continue
        rel = (b - a) / abs(a) if a else float("inf")
        if abs(rel) > tolerance:
            drifted.append(f"  {path}: {a:g} -> {b:g} ({rel:+.1%})")
    if drifted:
        lines.append(f"drift beyond {tolerance:.0%} in "
                     f"{len(drifted)} nested leaf/leaves (informational):")
        lines.extend(drifted)
    else:
        lines.append(f"no nested leaf drifted beyond {tolerance:.0%}")
    only_old = sorted(set(flat_old) - set(flat_new))
    only_new = sorted(set(flat_new) - set(flat_old))
    if only_old:
        lines.append(f"leaves only in OLD: {', '.join(only_old[:8])}"
                     + (" ..." if len(only_old) > 8 else ""))
    if only_new:
        lines.append(f"leaves only in NEW: {', '.join(only_new[:8])}"
                     + (" ..." if len(only_new) > 8 else ""))
    return lines, regressed


def compare_history(old_dir: str, new_dir: str, tolerance: float,
                    metric: str | None):
    """(report lines, regressed?) for two metrics-history windows.

    The gated value is ``obs.history.window_rate`` of ``metric`` (a
    cumulative counter; default jobs_completed_total) over each retained
    window. Direction is inferred from the metric name exactly like the
    artifact lane (a latency-named counter would gate lower-better)."""
    from gol_tpu.obs import history

    name = metric or "jobs_completed_total"
    rates = {}
    for label, directory in (("OLD", old_dir), ("NEW", new_dir)):
        if not os.path.isdir(directory):
            raise ValueError(f"{label} {directory!r} is not a history "
                             "directory")
        wr = history.window_rate(directory, name)
        if wr is None:
            raise ValueError(
                f"{label} history {directory!r} holds no measurable window "
                f"of counter {name!r} (needs >= 2 samples carrying it)"
            )
        rates[label] = wr
    (v_old, s_old), (v_new, s_new) = rates["OLD"], rates["NEW"]
    lower = lower_is_better(name, "")
    rel = (v_new - v_old) / abs(v_old) if v_old else 0.0
    bad = rel > tolerance if lower else rel < -tolerance
    better = rel < -tolerance if lower else rel > tolerance
    verdict = ("REGRESSION" if bad
               else "improvement" if better else "within tolerance")
    lines = [
        f"history window rate of {name} ({'lower' if lower else 'higher'} "
        f"is better): {v_old:g}/s (over {s_old:.1f}s) -> {v_new:g}/s "
        f"(over {s_new:.1f}s) ({rel:+.1%}) — {verdict}",
    ]
    return lines, bad


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("old", help="baseline BENCH_*.json "
                        "(or, with --history, a metrics-history dir)")
    parser.add_argument("new", help="candidate BENCH_*.json "
                        "(or, with --history, a metrics-history dir)")
    parser.add_argument(
        "--tolerance", type=float, default=0.10,
        help="relative noise threshold (default 0.10 = 10%%)",
    )
    parser.add_argument(
        "--metric", default=None, metavar="DOT.PATH",
        help="gate on this flattened nested leaf (e.g. "
        "lanes.fleet_n4.jobs_per_sec) instead of the headline 'value'; "
        "direction is inferred from the path (seconds/latency = lower is "
        "better)",
    )
    parser.add_argument(
        "--history", action="store_true",
        help="OLD/NEW are metrics-history directories "
        "(--metrics-history rings); gate the whole-window rate of the "
        "--metric counter (default jobs_completed_total) instead of a "
        "bench artifact headline",
    )
    args = parser.parse_args(argv)
    if args.tolerance < 0:
        print(f"bench-diff: tolerance must be >= 0, got {args.tolerance}",
              file=sys.stderr)
        return 2
    if args.history:
        try:
            lines, regressed = compare_history(
                args.old, args.new, args.tolerance, args.metric
            )
        except ValueError as err:
            print(f"bench-diff: {err}", file=sys.stderr)
            return 2
        print(f"bench-diff (history): {args.old} -> {args.new} "
              f"(tolerance {args.tolerance:.0%})")
        for line in lines:
            print(line)
        return 1 if regressed else 0
    docs = []
    for path in (args.old, args.new):
        try:
            with open(path, "r", encoding="utf-8") as f:
                docs.append(json.load(f))
        except (OSError, ValueError) as err:
            print(f"bench-diff: cannot read {path}: {err}", file=sys.stderr)
            return 2
    try:
        lines, regressed = compare(docs[0], docs[1], args.tolerance,
                                   metric=args.metric)
    except ValueError as err:
        print(f"bench-diff: {err}", file=sys.stderr)
        return 2
    print(f"bench-diff: {args.old} -> {args.new} "
          f"(tolerance {args.tolerance:.0%})")
    for line in lines:
        print(line)
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
