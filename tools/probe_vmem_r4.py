"""Map the temporal kernels' Mosaic-compile boundary near the width cap.

The advisor flagged that ``_bandt_target`` only drops to the 1MB band target
at exactly ``nwords >= _MAX_WORDS_T``, while the scoped-VMEM live set it
guards against grows continuously with width — so near-cap widths (roughly
7200-8191 words) under the 2MB target were suspected to Mosaic-OOM. This
probe compiles every temporal form at a ladder of widths x band targets on
the real chip and records pass/fail plus the verbatim error text (the error
strings also pin ``engine._is_compile_failure`` — see
tests/test_engine.py::test_compile_failure_real_error_text).

    python tools/probe_vmem_r4.py          # full matrix -> benchmarks/vmem_probe_r4.json

Compile-only (``.lower().compile()``): no data upload, each probe costs one
remote compile (~20-40s cold).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from gol_tpu.ops import stencil_packed as sp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "benchmarks", "vmem_probe_r4.json")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _compile(form: str, height: int, nwords: int, target: int):
    """Lower+compile one temporal form at an explicit band target.

    Patches ``sp._bandt_target`` (the selection under probe) and clears the
    step functions' jit caches so every probe re-traces with its own target.
    """
    band = sp._pick_band(height, nwords, target)
    words = jax.ShapeDtypeStruct((height, nwords), jnp.uint32)
    g8 = jax.ShapeDtypeStruct((sp.TEMPORAL_GENS, nwords), jnp.uint32)
    gext = jax.ShapeDtypeStruct((height + 2 * sp.TEMPORAL_GENS, 2), jnp.uint32)

    orig = sp._bandt_target
    sp._bandt_target = lambda *a, **k: target
    try:
        if form == "t":  # single-device torus (_bandt_kernel)
            sp._step_t.clear_cache()
            sp._step_t.lower(words).compile()
        elif form == "trow":  # rows-only mesh shard (_bandtrow_kernel)
            sp._step_trow.clear_cache()
            sp._step_trow.lower(words, g8, g8).compile()
        elif form == "tgb":  # 2D mesh shard w/ ghost plane (_bandtg_kernel)
            sp._step_tgb.clear_cache()
            sp._step_tgb.lower(words, g8, g8, gext).compile()
        else:
            raise ValueError(form)
    finally:
        sp._bandt_target = orig
    return band


def main() -> None:
    assert jax.default_backend() == "tpu", jax.default_backend()
    height = 1024
    results = []
    error_samples = {}
    # Widths from the proven-safe 2048 words (65536^2 single chip) up to the
    # cap, plus the advisor's named 8184; targets 2MB (current wide default),
    # 1.5MB, 1MB (current at-cap value).
    widths = [2048, 3072, 4096, 5120, 6144, 7168, 7680, 8184, 8192]
    targets = [2 << 20, 3 << 19, 1 << 20]
    for form in ("t", "trow", "tgb"):
        for nwords in widths:
            for target in targets:
                t0 = time.time()
                try:
                    band = _compile(form, height, nwords, target)
                    ok, err_type, err_text = True, None, None
                    log(f"{form} {nwords}w target={target>>20}MB band={band}: OK "
                        f"({time.time()-t0:.0f}s)")
                except Exception as e:  # noqa: BLE001 - recording, not handling
                    ok = False
                    err_type = f"{type(e).__module__}.{type(e).__name__}"
                    err_text = str(e)
                    band = sp._pick_band(height, nwords, target)
                    log(f"{form} {nwords}w target={target>>20}MB band={band}: "
                        f"FAIL {err_type}: {err_text[:120]} ({time.time()-t0:.0f}s)")
                    error_samples.setdefault(err_type, err_text[:4000])
                results.append({
                    "form": form, "height": height, "nwords": nwords,
                    "target_bytes": target, "band": band, "ok": ok,
                    "err_type": err_type,
                    "err_head": err_text[:300] if err_text else None,
                    "secs": round(time.time() - t0, 1),
                })
                _dump(results, error_samples)

    # One guaranteed-huge failure for error-text capture: double the cap.
    for form, nwords in (("t", 16384),):
        try:
            _compile(form, height, nwords, 1 << 20)
            log(f"{form} {nwords}w: unexpectedly OK")
        except Exception as e:  # noqa: BLE001
            err_type = f"{type(e).__module__}.{type(e).__name__}"
            error_samples.setdefault(err_type, str(e)[:4000])
            log(f"{form} {nwords}w: FAIL {err_type} (captured)")

    # An HBM RESOURCE_EXHAUSTED for the other error family: ~32GB on a 16GB
    # chip, at execute time.
    try:
        jnp.zeros((2 << 30, 16), jnp.uint8).block_until_ready()
        log("HBM probe: unexpectedly OK")
    except Exception as e:  # noqa: BLE001
        err_type = f"{type(e).__module__}.{type(e).__name__}"
        error_samples.setdefault("hbm:" + err_type, str(e)[:4000])
        log(f"HBM probe: FAIL {err_type} (captured)")
    _dump(results, error_samples)
    log("wrote", OUT)


def _dump(results, error_samples):
    with open(OUT, "w") as f:
        json.dump({
            "purpose": "near-cap Mosaic compile boundary, r4 (advisor medium)",
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "probes": results,
            "error_samples": error_samples,
        }, f, indent=1)
        f.write("\n")


if __name__ == "__main__":
    main()
