"""TPU kernel soak: compiled Mosaic kernels vs the jnp adder network.

Random (height, words) shapes on the attached chip; every compiled path
(single-gen band kernel, 1-gen mesh form, T=8 temporal, banded-operand mesh
temporal, byte band kernel) must match the jnp reference exactly:

    python tools/soak_tpu.py [seconds=900]

The seed is taken from the clock and printed, so every run explores new
shapes and any failure is replayable. Round-2 record: 213 shapes across
three runs (compiles dominate the wall clock), all identical. Round-3
record: 94 shapes across three runs (seeds 1785501403, 1785510712,
1785520194 — the later two with each draw soaking BOTH mesh temporal
forms, rows-only via SINGLE_DEVICE and ghost-plane via the cols=2
proxy), all identical; an
earlier run died mid-way on a remote-compile service SIGTERM
(infrastructure, not a kernel failure) — don't co-schedule the CPU
soak's compile storm with this one on a shared host.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax
import jax.numpy as jnp

from gol_tpu.ops import packed_math, stencil_lax, stencil_packed as sp, stencil_pallas as spl
from gol_tpu.parallel.mesh import PROXY_2D, SINGLE_DEVICE

if jax.default_backend() != "tpu":
    print("soak_tpu needs an attached TPU backend")
    sys.exit(1)
DEADLINE = time.time() + (float(sys.argv[1]) if len(sys.argv) > 1 else 900)
seed0 = int(time.time())
print(f"soak seed: {seed0}", flush=True)
rng = np.random.default_rng(seed0)


def check(name, got, want, shape):
    if not np.array_equal(np.asarray(got), np.asarray(want)):
        print("MISMATCH", name, shape)
        sys.exit(1)


count = 0
while time.time() < DEADLINE:
    h = int(rng.integers(1, 65)) * 8
    nw = int(rng.integers(1, 96))
    words = jnp.asarray(rng.integers(0, 2**32, size=(h, nw), dtype=np.uint32))
    ref1 = packed_math.evolve_torus_words(words)
    check("single-gen", sp._step(words)[0], ref1, (h, nw))
    check("dist-1gen", sp._distributed_step(words, SINGLE_DEVICE)[0], ref1, (h, nw))
    if sp.supports_multi(h, nw * 32, SINGLE_DEVICE) and h >= 16:
        cur = words
        for _ in range(sp.TEMPORAL_GENS):
            cur = packed_math.evolve_torus_words(cur)
        check("temporal", sp._step_t(words)[0], cur, (h, nw))
        # SINGLE_DEVICE has cols == 1: the rows-only kernel. The cols > 1
        # proxy draws the 2D ghost-plane form (what R x C pod chips run)
        # with local wraps, so BOTH compiled mesh forms stay fuzzed.
        check(
            "dist-temporal-rows",
            sp._distributed_step_multi(words, SINGLE_DEVICE)[0],
            cur,
            (h, nw),
        )
        check(
            "dist-temporal-2d",
            sp._distributed_step_multi(words, PROXY_2D)[0],
            cur,
            (h, nw),
        )
    # byte kernel on lane-aligned shapes
    if nw % 4 == 0 and nw >= 4:
        g = jnp.asarray(rng.integers(0, 2, size=(h, nw * 32), dtype=np.uint8))
        check("byte-band", spl._step(g)[0], stencil_lax.evolve_torus(g), (h, nw))
    count += 1
    if count % 10 == 0:
        print(f"{count} shapes OK", flush=True)
print(f"TPU SOAK PASS: {count} random shapes, all kernel paths network-identical")
