"""Tune-subsystem smoke: search -> persist -> reload -> oracle-exact replay.

The `make tune-smoke` gate. On CPU, with a tiny search space, it drives the
whole autotune loop end to end and fails loudly if any link breaks:

1. **search** — `gol tune --quick` over a small grid (both conventions) and
   the serve geometry, every candidate byte-gated in-process;
2. **persist** — plans land in a throwaway cache file (atomic write path);
3. **reload** — a FRESH process (`gol run` with GOL_PLAN_CACHE pointing at
   the cache) consults the plan, logs the tuned selection, and its output
   file byte-matches `--host` (the NumPy oracle) on the same input — i.e.
   the selected plan *reproduces oracle output*, not just "runs";
4. **no-plan identity** — the same run against an empty cache produces the
   same bytes (plans are performance-only by construction).

Exit 0 on success, 1 with a diagnostic on any failure.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SIZE = 48
GENS = 40


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def run(args, env, cwd, check=True):
    proc = subprocess.run(
        [sys.executable, "-m", "gol_tpu", *args],
        capture_output=True, text=True, cwd=cwd, env=env, timeout=600,
    )
    if check and proc.returncode != 0:
        log(f"FAIL: gol {' '.join(args)} -> rc {proc.returncode}")
        log(proc.stdout[-2000:])
        log(proc.stderr[-2000:])
        raise SystemExit(1)
    return proc


def main() -> int:
    td = tempfile.mkdtemp(prefix="gol_tune_smoke_")
    cache = os.path.join(td, "plans.json")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["GOL_PLAN_CACHE"] = cache

    inp = os.path.join(td, "input.txt")
    run(["generate", str(SIZE), str(SIZE), "--seed", "7", "-o", inp], env, td)

    log(f"[1/4] search (quick, {SIZE}x{SIZE}, both conventions + serve)")
    run(
        ["tune", "--shape", f"{SIZE}x{SIZE}", "--convention", "both",
         "--gen-limit", "24", "--iters", "3", "--quick",
         "--serve-board", f"{SIZE}x{SIZE}",
         "--report", os.path.join(td, "report.md")],
        env, td,
    )

    log("[2/4] persist: cache file parses and holds plans")
    with open(cache, encoding="utf-8") as f:
        body = json.load(f)
    kinds = sorted(
        key.split("kind=")[1].split("|")[0] for key in body["plans"]
    )
    if kinds.count("engine") != 2 or "serve" not in kinds:
        log(f"FAIL: expected 2 engine plans + 1 serve plan, got keys {kinds}")
        return 1
    log(f"  {len(body['plans'])} plan(s) persisted")

    log("[3/4] reload: fresh process consults the plan, output == oracle")
    for variant, conv in (("tpu", "c"), ("cuda", "cuda")):
        tuned_out = os.path.join(td, f"tuned_{conv}.out")
        proc = run(
            [str(SIZE), str(SIZE), inp, "--variant", variant,
             "--gen-limit", str(GENS), "--output", tuned_out],
            env, td,
        )
        if "tuned engine plan" not in proc.stderr:
            log(f"FAIL: {conv}: no 'tuned engine plan' consult logged\n"
                f"{proc.stderr[-800:]}")
            return 1
        oracle_out = os.path.join(td, f"oracle_{conv}.out")
        host_variant = "game" if variant == "tpu" else variant
        run(
            [str(SIZE), str(SIZE), inp, "--variant", host_variant, "--host",
             "--gen-limit", str(GENS), "--output", oracle_out],
            env, td,
        )
        with open(tuned_out, "rb") as f1, open(oracle_out, "rb") as f2:
            if f1.read() != f2.read():
                log(f"FAIL: {conv}: tuned output differs from the oracle")
                return 1
        log(f"  {conv}: tuned plan reproduces oracle output")

    log("[4/4] no-plan identity: empty cache produces identical bytes")
    env_empty = dict(env)
    env_empty["GOL_PLAN_CACHE"] = os.path.join(td, "missing", "plans.json")
    for conv, variant in (("c", "tpu"), ("cuda", "cuda")):
        plain_out = os.path.join(td, f"plain_{conv}.out")
        proc = run(
            [str(SIZE), str(SIZE), inp, "--variant", variant,
             "--gen-limit", str(GENS), "--output", plain_out],
            env_empty, td,
        )
        if "tuned engine plan" in proc.stderr:
            log(f"FAIL: {conv}: consult hit with an empty cache")
            return 1
        with open(plain_out, "rb") as f1, \
                open(os.path.join(td, f"tuned_{conv}.out"), "rb") as f2:
            if f1.read() != f2.read():
                log(f"FAIL: {conv}: tuned and un-tuned outputs differ")
                return 1
    log("tune-smoke PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
