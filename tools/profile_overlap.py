"""Decompose the mesh-temporal step's cost on the chip.

    python tools/profile_overlap.py [size] [N2]

``N2`` is the long-chain call count (the short chain is N2 // 3); scale it
inversely with the per-call time — the tunnel's ~10 ms timing jitter is
divided by (N2 - N1), so a 16384^2 grid (~0.5 ms/call) needs chains ~8x
longer than 32768^2 for the same resolution.

Methodology matches tools/measure_r3.py: every figure is a MARGINAL rate —
time a fori_loop chain of N1 calls and one of N2 > N1 calls, each forced by
an int() readback of one element, and report (t2 - t1) / (N2 - N1). The
attach tunnel's ~90 ms fixed round trip and any dispatch cost cancel in the
difference (block_until_ready does not reliably block under axon); chip
throughput still drifts minute-to-minute, so treat ratios from ONE run as
the signal and absolute ms as indicative.

This tool's r3 measurements drove the retirement of the overlapped
interior/frontier split (benchmarks/compare_32768_r3.json): the frontier
kernels (T-row strips, a 6-lane edge-column plane, stitch) cost ~0.8x of
the main kernel — tiny-kernel launches and strided column extraction are
pathological on TPU — to hide an exchange measuring ~0.15x on-chip and
tens of microseconds over real ICI.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp


def log(*a):
    print(*a, file=sys.stderr, flush=True)


N1, N2 = 25, 75
REPEATS = 3


def probes(words, sp, SINGLE_DEVICE):
    """(name, state->state) pieces of the mesh temporal step.

    The 2D (ghost-plane) form is decomposed against the PROXY_2D topology
    — SINGLE_DEVICE (cols == 1) routes _distributed_step_multi through the
    rows-only kernel, a different composition, profiled as its own lane.
    """
    from gol_tpu.parallel.mesh import PROXY_2D as proxy_2d

    gtop, gbot, G_ext = jax.jit(
        lambda w: sp.deep_ghost_operands(w, proxy_2d))(words)
    int(gtop[0, 0])

    # Exchange alone, chained by writing one ghost word back into the state
    # (keeps a data dependence so the loop can't collapse).
    def ghost_step(w):
        gt, gb, ge = sp.deep_ghost_operands(w, proxy_2d)
        return jax.lax.dynamic_update_slice(w, gt[0:1, 0:1], (0, 0))

    return [
        ("step_t", lambda w: sp._step_t(w)[0]),
        # Kernel alone: ghosts precomputed once outside the chain. The chain
        # feeds the kernel its own output with FIXED ghosts — wrong math,
        # right cost (shapes and memory traffic match the real pass).
        ("tgb_kernel_only",
         lambda w: sp._step_tgb(w, gtop, gbot, G_ext)[0]),
        ("ghosts_only", ghost_step),
        ("mesh_2d_full",
         lambda w: sp._distributed_step_multi(w, proxy_2d)[0]),
        ("mesh_rows_full",
         lambda w: sp._distributed_step_multi(w, SINGLE_DEVICE)[0]),
    ]


def marginal(step, state):
    """Marginal seconds per call of ``step`` (state -> state), chained."""
    times = {}
    for calls in (N1, N2):
        run = jax.jit(
            lambda s, n=calls: jax.lax.fori_loop(
                0, n, lambda i, x: step(x), s
            )[0, 0]
        )
        int(run(state))  # compile + settle
        best = float("inf")
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            out = int(run(state))
            best = min(best, time.perf_counter() - t0)
        times[calls] = best
    return (times[N2] - times[N1]) / (N2 - N1)


def main() -> int:
    global N1, N2
    size = int(sys.argv[1]) if len(sys.argv) > 1 else 32768
    if len(sys.argv) > 2:
        N2 = max(2, int(sys.argv[2]))
        N1 = max(1, N2 // 3)
        if N1 == N2:
            N1 = N2 - 1
    from gol_tpu.ops import stencil_packed as sp
    from gol_tpu.parallel.mesh import SINGLE_DEVICE

    rng = np.random.default_rng(42)
    grid = rng.integers(0, 2, size=(size, size), dtype=np.uint8)
    words = jnp.asarray(
        np.packbits(grid, axis=1, bitorder="little").view(np.uint32)
    )
    words.block_until_ready()
    h, nwords = words.shape
    log(f"shard {h}x{nwords} words, T={sp.TEMPORAL_GENS}; "
        f"marginal over {N1}->{N2} calls")

    results = {}
    for name, step in probes(words, sp, SINGLE_DEVICE):
        t = marginal(step, words)
        results[name] = t
        log(f"{name:20s} {t*1e3:8.3f} ms/call")

    log("---")
    base = results["step_t"]
    for k, v in results.items():
        log(f"{k:20s} {v / base:6.2f}x of step_t")
    return 0


if __name__ == "__main__":
    sys.exit(main())
