"""Thin shim: the r3 measurement battery lives in tools/measure.py (--rev 3).

Kept so documented commands (`python tools/measure_r3.py h2d` etc.) keep
working; new work goes through `python tools/measure.py --rev 3 <step>`.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from measure import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--rev", "3", *sys.argv[1:]]))
