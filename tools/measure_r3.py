"""Thin shim: the r3 measurement battery lives in tools/measure.py (--rev 3).

Kept so documented commands (`python tools/measure_r3.py h2d` etc.) keep
working; the argument mapping lives in measure.py's ``_SHIM_ARGS`` table.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from measure import shim_main  # noqa: E402

if __name__ == "__main__":
    sys.exit(shim_main(__file__))
