"""Round-3 TPU measurement battery — run when a real chip is attached.

Each step is independently invocable (the attach tunnel can drop mid-way):

    python tools/measure_r3.py compare32k   # single-chip vs mesh-form temporal
    python tools/measure_r3.py h2d          # codec pack + host->device probes
    python tools/measure_r3.py d2h          # raw/chunked device->host probes
    python tools/measure_r3.py config5      # 65536^2 end-to-end CLI phases
    python tools/measure_r3.py all

Artifacts land in benchmarks/ as *_r3.json. The hardware test lane writes
its own artifact: GOL_TPU_HW=1 python -m pytest tests/test_tpu_hw.py -q.

Uploads use host-side packbits (128MB of words, not 1GB of bytes — the
attach tunnel makes the byte-grid upload the slowest part of any 32768+
measurement).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "benchmarks")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _host_words(size: int, seed: int = 42) -> np.ndarray:
    rng = np.random.default_rng(seed)
    grid = rng.integers(0, 2, size=(size, size), dtype=np.uint8)
    return np.packbits(grid, axis=1, bitorder="little").view(np.uint32)


def _write(name: str, payload: dict) -> None:
    path = os.path.join(OUT, name)
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    log("wrote", path)


def compare32k(size: int = 32768, g1: int = 200, repeats: int = 5) -> None:
    """Mesh-form A/B: single-chip temporal vs the banded mesh form, marginal
    over g1 -> 3*g1 generations. Repeats are INTERLEAVED across paths (all
    four chains timed round-robin) so the chip's minute-scale throughput
    drift — measured up to 35% between back-to-back processes on the shared
    attach tunnel — cancels out of the ratio instead of biasing one path."""
    import jax
    import jax.numpy as jnp

    from gol_tpu.ops import stencil_packed as sp
    from gol_tpu.parallel.mesh import PROXY_2D, SINGLE_DEVICE

    words = jnp.asarray(_host_words(size))
    words.block_until_ready()
    log("words on device")

    def loop(step, calls):
        def run(state):
            final = jax.lax.fori_loop(0, calls, lambda i, s: step(s), state)
            return final[0, 0]

        return jax.jit(run)

    proxy_2d = PROXY_2D  # cols>1: ghost-plane form
    paths = {
        "packed-temporal-T8": lambda w: sp._step_t(w)[0],
        # cols == 1 -> the rows-only kernel (R x 1 pod layout, full-width
        # shards, no ghost-column machinery).
        "packed-dist-temporal": lambda w: sp._distributed_step_multi(
            w, SINGLE_DEVICE
        )[0],
        # cols > 1 with local wraps -> the 2D-mesh ghost-plane form.
        "packed-dist-temporal-2d": lambda w: sp._distributed_step_multi(
            w, proxy_2d
        )[0],
    }
    g2 = 3 * g1
    runs, best = {}, {}
    for name, step in paths.items():
        for gens in (g1, g2):
            run = loop(step, gens // sp.TEMPORAL_GENS)
            int(run(words))
            log("compiled", name, gens)
            runs[name, gens] = run
            best[name, gens] = float("inf")
    for rep in range(repeats):
        for key, run in runs.items():
            t0 = time.perf_counter()
            int(run(words))
            best[key] = min(best[key], time.perf_counter() - t0)
        log(f"rep {rep + 1}/{repeats} done")
    res = {}
    for name in paths:
        marg = (best[name, g2] - best[name, g1]) / (g2 - g1)
        res[name] = size * size / marg
        log(f"{name:26s} {marg * 1e3:8.3f} ms/gen  {res[name]:.3e} cells/s")
    ratio = res["packed-dist-temporal"] / res["packed-temporal-T8"]
    ratio_2d = res["packed-dist-temporal-2d"] / res["packed-temporal-T8"]
    _write(
        f"compare_{size}_r3.json",
        {
            "metric": "dist_temporal_vs_single_chip",
            "value": ratio,
            "unit": "ratio",
            "vs_baseline": None,
            "detail": res,
            "ratio_2d_form": ratio_2d,
            "size": size,
            "generations": [g1, g2],
            "note": (
                "marginal rates, fixed-count fori_loop, one chip, repeats "
                "interleaved across paths to cancel the tunnel chip's "
                "minute-scale drift. packed-dist-temporal is the rows-only "
                "kernel (R x 1 pod layout: full-width shards, E/W wrap = "
                "own lane roll, no ghost-column machinery); -2d is the "
                "ghost-plane form an R x C pod chip runs. The r3 "
                "overlapped interior/frontier split measured 0.40 vs the "
                "2d form's 0.49-0.88 across sessions and was retired — "
                "its frontier kernels cost ~0.8x of the main kernel to "
                "hide an exchange costing ~0.15x on-chip (see "
                "stencil_packed._distributed_step_multi)."
            ),
        },
    )


def h2d(size: int = 65536) -> None:
    """Read-phase decomposition: codec pack throughput (text bytes -> packed
    words, host-only) and host->device upload throughput, measured apart so
    the config5 Reading-file number has a written breakdown — which side is
    the bound, storage/codec or the attach tunnel."""
    import jax

    from gol_tpu import native
    from gol_tpu.io.text_grid import row_stride

    rng = np.random.default_rng(7)
    rows = 8192  # 8192 x 65537 text bytes ~ 512MB sample of the 4.3GB file
    text = rng.integers(ord("0"), ord("2"), size=(rows, row_stride(size)),
                        dtype=np.uint8)
    text[:, -1] = ord("\n")
    t0 = time.perf_counter()
    packed = native.pack_text(text, size)
    pack_s = time.perf_counter() - t0
    text_mb = text.nbytes / (1 << 20)

    words = rng.integers(0, 2**32, size=(size, size // 32), dtype=np.uint32)
    t0 = time.perf_counter()
    jax.device_put(words).block_until_ready()
    # block_until_ready can return early over the tunnel; settle with a
    # tiny readback tied to the uploaded buffer.
    up = jax.device_put(words)
    int(up[0, 0])
    h2d_s = (time.perf_counter() - t0) / 2  # two uploads timed
    mb = words.nbytes / (1 << 20)
    _write(
        "h2d_probe_r3.json",
        {
            "metric": "h2d_throughput",
            "value": mb / h2d_s,
            "unit": "MB/s",
            "vs_baseline": None,
            "detail": {
                "pack_text_MBps": round(text_mb / pack_s, 1),
                "pack_sample_bytes": text.nbytes,
                "h2d_s_per_512MB": round(h2d_s, 3),
            },
            "bytes": words.nbytes,
            "note": "codec pack rate is per-thread (read_packed fans it "
            "over a pool); upload is one 512MB device_put over the attach "
            "tunnel — together they bound the packed read phase.",
        },
    )


def d2h(size: int = 65536) -> None:
    """Device->host throughput probes for the write phase: one-shot vs
    chunked at prefetch depths 1, 2 and 4 (the packed_io pipeline's knob)."""
    import jax
    import jax.numpy as jnp

    from gol_tpu.io import packed_io

    nwords = size // 32
    rng = np.random.default_rng(1)
    host = rng.integers(0, 2**32, size=(size, nwords), dtype=np.uint32)
    words = jnp.asarray(host)
    words.block_until_ready()
    log("words on device:", host.nbytes >> 20, "MB")
    results = {}

    t0 = time.perf_counter()
    np.asarray(words)
    results["oneshot_s"] = time.perf_counter() - t0

    chunk_rows = max(1, packed_io._WRITE_CHUNK_BYTES // (nwords * 4))
    for depth in (1, 2, 4):
        import concurrent.futures

        starts = list(range(0, size, chunk_rows))
        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(max_workers=depth) as pool:
            blocks = list(
                pool.map(
                    lambda s: np.ascontiguousarray(words[s : s + chunk_rows]),
                    starts,
                )
            )
        results[f"chunked_depth{depth}_s"] = time.perf_counter() - t0
        del blocks
    mb = host.nbytes / (1 << 20)
    _write(
        "d2h_probe_r3.json",
        {
            "metric": "d2h_throughput",
            "value": mb / results["oneshot_s"],
            "unit": "MB/s",
            "vs_baseline": None,
            "detail": {k: round(v, 3) for k, v in results.items()},
            "bytes": host.nbytes,
            "note": "device->host transfer probes over the attach tunnel; "
            "chunked figures include the per-chunk device slice dispatch.",
        },
    )


def config5(size: int = 65536, gens: int = 10000) -> None:
    """The north-star workload end-to-end through the CLI, phases recorded."""
    import re
    import subprocess
    import tempfile

    td = tempfile.mkdtemp(prefix="gol_config5_")
    inp = os.path.join(td, "input.txt")
    env = dict(os.environ)
    # The package is not installed; prepend (don't clobber — it carries the
    # TPU backend registration) the repo onto PYTHONPATH.
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    log("generating", size, "input at", inp)
    subprocess.run(
        [sys.executable, "-m", "gol_tpu", "generate", str(size), str(size),
         "--seed", "5", "--output", inp],
        check=True, cwd=REPO, env=env,
    )
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "gol_tpu", str(size), str(size), inp,
         "--variant", "tpu", "--packed-io", "--warmup",
         "--gen-limit", str(gens)],
        capture_output=True, text=True, check=True, cwd=td, env=env,
    )
    wall = time.perf_counter() - t0
    log(proc.stdout)
    phases = dict(
        re.findall(r"(Reading file|Execution time|Writing file):\t([0-9.]+)",
                   proc.stdout)
    )
    generations = int(re.search(r"Generations:\t(\d+)", proc.stdout).group(1))
    exec_s = float(phases["Execution time"]) / 1000
    rate = size * size * generations / exec_s
    _write(
        "config5_r3.json",
        {
            "metric": "cell_updates_per_sec_per_chip",
            "value": rate,
            "unit": "cells/s",
            "vs_baseline": rate / 1e11,
            "phases_ms": {k: float(v) for k, v in phases.items()},
            "generations": generations,
            "wall_s": round(wall, 1),
            "size": size,
            "note": "BASELINE.md config 5 end-to-end via the CLI on one "
            "chip: packed I/O + temporal kernel + chunked D2H write "
            "pipeline at depth GOL_D2H_DEPTH (default 2). Read/write "
            "phases ride the attach tunnel, whose throughput drifts "
            "several-x between sessions (benchmarks/d2h_probe_r3.json "
            "records the same-session transfer floor); Execution time is "
            "on-device and comparable across sessions (r2: exec 16.4s, "
            "write 25.5s, read 10.1s).",
        },
    )


STEPS = {"compare32k": compare32k, "h2d": h2d, "d2h": d2h, "config5": config5}


def main() -> int:
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    names = list(STEPS) if which == "all" else [which]
    for name in names:
        log("=== step:", name)
        STEPS[name]()
    return 0


if __name__ == "__main__":
    sys.exit(main())
