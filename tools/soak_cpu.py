"""Extended randomized differential soak: engine vs oracle on CPU meshes.

Open-ended fuzz over mesh shapes (1x1..8x1) x kernels (lax/auto/packed/pallas)
x conventions x similarity frequencies x densities x generation limits, every
case byte-compared against the NumPy oracle:

    python tools/soak_cpu.py [seconds=1800]

(The 8-virtual-device XLA flag is set automatically when absent.) Prints the
per-kernel case counts at the end so coverage of each path is visible —
pallas cases need 128-lane local shards, so their draws use wider grids.
Round-2 record: 2828 cases across five runs; round-3 record: 3042 cases
across ten runs (longest: 673 cases with 145 segmented and 138 resumed
replays; the last two runs, 568 + 483 cases, drew 'packed-interp' through
the post-rows-only routing — R x 1 meshes take _step_trow, cols > 1 the
banded ghost-plane kernel), all oracle-identical. The pytest suite pins
fixed cases; this explores the space around them.
"""
import collections
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

from gol_tpu import engine, oracle
from gol_tpu.config import Convention, GameConfig
from gol_tpu.ops import stencil_packed as _sp
from gol_tpu.parallel.mesh import choose_mesh_shape, make_mesh

DEADLINE = time.time() + (float(sys.argv[1]) if len(sys.argv) > 1 else 1800)
seed0 = int(time.time())
print(f"soak seed: {seed0}", flush=True)
rng = np.random.default_rng(seed0)
meshes = [None, (1, 2), (2, 1), (2, 2), (2, 4), (4, 2), (1, 8), (8, 1)]
kernels = ["lax", "auto", "packed", "pallas"]
counts = collections.Counter()
_ORIG_CAP = _sp._MAX_WORDS_T
_cap_patched = False
while time.time() < DEADLINE:
    if _cap_patched:
        # Restore the real cap and drop runners compiled under the patched
        # one (cache keys don't see the cap, so stale entries would mix
        # routings across draws).
        _sp._MAX_WORDS_T = _ORIG_CAP
        engine.make_runner.cache_clear()
        engine.make_segment_runner.cache_clear()
        _cap_patched = False
    ms = meshes[rng.integers(len(meshes))]
    r, c = ms if ms else (1, 1)
    kernel = kernels[rng.integers(len(kernels))]
    hk = int(rng.integers(1, 4))
    # The byte pallas kernel needs 128-lane local shards; give its draws
    # (and some others) wide-enough grids instead of silently skipping.
    wk = 4 if kernel == "pallas" or rng.random() < 0.25 else int(rng.integers(1, 3))
    h, w = r * hk * 8, c * wk * 32
    conv = Convention.CUDA if rng.random() < 0.5 else Convention.C
    freq = int(rng.integers(1, 5))
    check = bool(rng.random() < 0.9)
    lim = int(rng.integers(1, 40))
    density = float(rng.random())
    seed = int(rng.integers(2**31))
    # A slice of packed mesh draws routes through the interpret-mode Mosaic
    # kernels (kernel='packed-interp') so the banded deep-halo temporal
    # composition gets fuzzed, not just the jnp network. A first-class
    # kernel name, so runner caches key correctly with no global-flag
    # toggling. Interpret mode is slow: small shapes, short runs.
    force_kernel = (
        kernel == "packed" and ms is not None and rng.random() < 0.08
    )
    if force_kernel:
        kernel = "packed-interp"
        hk = min(hk, 2)
        h, w = r * hk * 8, c * wk * 32
        # Two temporal passes plus a single-generation tail.
        lim = min(lim, 2 * _sp.TEMPORAL_GENS + 3)
    cap_patch = None
    if ms and kernel in ("packed", "auto") and not force_kernel and rng.random() < 0.10:
        # Width-cap seam fuzz (VERDICT r3 item 8): shrink the temporal
        # width cap to 1-3 words so CPU-scale shards straddle it — the
        # choose_mesh_shape column-adding seam picks the mesh, and
        # supports_multi flips the temporal/per-generation routing right at
        # the boundary. Both routes must stay oracle-exact.
        cap_patch = int(rng.integers(1, 4))
        _sp._MAX_WORDS_T = cap_patch
        _cap_patched = True
        engine.make_runner.cache_clear()
        engine.make_segment_runner.cache_clear()
        r2, c2 = choose_mesh_shape(8, width=w, height=h)
        if h % r2 == 0 and w % (32 * c2) == 0:
            r, c, ms = r2, c2, (r2, c2)
    g = (np.random.default_rng(seed).random((h, w)) < density).astype(np.uint8)
    cfg = GameConfig(gen_limit=lim, similarity_frequency=freq,
                     check_similarity=check, convention=conv)
    case = dict(mesh=ms, shape=(h, w), kernel=kernel, conv=conv, freq=freq,
                check=check, lim=lim, density=round(density, 3), seed=seed,
                force_kernel=force_kernel, cap_patch=cap_patch)
    try:
        got = engine.simulate(g, cfg, mesh=make_mesh(r, c) if ms else None, kernel=kernel)
    except ValueError as e:
        # unsupported kernel/shape combos are loud errors by design
        if "does not support" in str(e) or "requires" in str(e):
            counts[f"{kernel}-unsupported"] += 1
            continue
        print("UNEXPECTED ERROR", case, e)
        sys.exit(1)
    want = oracle.run(g, cfg)
    if got.generations != want.generations or not np.array_equal(got.grid, want.grid):
        print("MISMATCH", case)
        sys.exit(1)
    counts[kernel] += 1
    if cap_patch is not None:
        counts["cap-seam"] += 1
    if rng.random() < 0.25:
        # Segmented replay: random segment lengths must reproduce the whole
        # run bit-exactly (the snapshot/resume property, with the similarity
        # phase carried across arbitrary segment boundaries).
        segment = int(rng.integers(1, lim + 2))
        seg_gens, seg_grid = 0, None
        for seg_gens, seg_grid, _stopped in engine.simulate_segments(
            g, cfg, make_mesh(r, c) if ms else None, kernel, segment
        ):
            pass
        seg_np = np.asarray(jax.device_get(seg_grid), dtype=np.uint8)
        if seg_gens != want.generations or not np.array_equal(seg_np, want.grid):
            print("SEGMENT MISMATCH", {**case, "segment": segment})
            sys.exit(1)
        counts["segmented"] += 1
    if rng.random() < 0.25 and want.generations > 1:
        # Resume replay: snapshot after a random split, continue with the
        # similarity phase realigned from the count alone (resume_scalars) —
        # must match the uninterrupted run, early exits included.
        split = int(rng.integers(1, want.generations))
        first = GameConfig(gen_limit=split,
                           similarity_frequency=freq,
                           check_similarity=check, convention=conv)
        snap = engine.simulate(g, first,
                               mesh=make_mesh(r, c) if ms else None,
                               kernel=kernel).grid
        res_gens, res_grid = 0, None
        for res_gens, res_grid, _stopped in engine.simulate_segments(
            snap, cfg, make_mesh(r, c) if ms else None, kernel,
            segment=int(rng.integers(1, lim + 2)), completed=split,
        ):
            pass
        res_np = np.asarray(jax.device_get(res_grid), dtype=np.uint8)
        if res_gens != want.generations or not np.array_equal(res_np, want.grid):
            print("RESUME MISMATCH", {**case, "split": split})
            sys.exit(1)
        counts["resumed"] += 1
    total = sum(v for k, v in counts.items()
                if not k.endswith("-unsupported") and k not in ("segmented", "resumed"))
    if total % 50 == 0:
        print(f"{total} cases OK {dict(counts)}", flush=True)
total = sum(v for k, v in counts.items()
            if not k.endswith("-unsupported") and k not in ("segmented", "resumed"))
print(f"SOAK PASS: {total} randomized cases, all oracle-identical; {dict(counts)}")
