"""Async-pipeline crash/restart smoke: kill mid-async-write, resume, verify.

The `make pipeline-smoke` harness, exercising both halves of
gol_tpu/pipeline against real OS processes:

1. **Checkpoint half** — a checkpointed run with the async writer (the
   default lane) is SIGKILLed while the background writer thread is
   mid-payload-write (``GOL_FAULTS=kill_during_ckpt_write=2,
   kill_mode=sigkill`` — no Python unwinding, like a power cut). The
   checkpoint committed by the *previous* boundary's deferred wait must
   survive; ``--auto-resume`` must complete the run to an output file
   byte-identical to an uninterrupted run's, reporting the same generation
   count. Then the same input is re-run with ``--sync-checkpoints`` to pin
   async/sync byte-compatibility end to end.

2. **Serve half** — a ``gol serve --pipeline-depth 2`` session takes jobs
   across two padding buckets, finishes them all, drains clean via POST
   /drain + SIGTERM, and the journal must show every accepted job DONE
   exactly once (the pipelined dispatcher/completer preserves the
   exactly-once ledger).

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/pipeline_smoke.py [--jobs 24] [--gen-limit 200]
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env(extra=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("GOL_FAULTS", None)
    if extra:
        env.update(extra)
    return env


def _gol(args, extra_env=None, check=True):
    proc = subprocess.run(
        [sys.executable, "-m", "gol_tpu", *args],
        env=_env(extra_env), cwd=ROOT, capture_output=True, text=True,
    )
    if check and proc.returncode != 0:
        raise RuntimeError(
            f"gol {' '.join(args)} rc={proc.returncode}:\n"
            f"{proc.stdout}\n{proc.stderr}"
        )
    return proc


def checkpoint_half(workdir: str) -> bool:
    infile = os.path.join(workdir, "in.txt")
    _gol(["generate", "64", "64", "--seed", "29", "-o", infile])
    gen_limit, every = 24, 6

    ref = os.path.join(workdir, "ref.out")
    ref_run = _gol(["run", "64", "64", infile, "--variant", "game",
                    "--gen-limit", str(gen_limit), "--output", ref])
    ref_gens = [l for l in ref_run.stdout.splitlines()
                if l.startswith("Generations")]

    ck = os.path.join(workdir, "ck")
    out = os.path.join(workdir, "out.out")
    base = ["run", "64", "64", infile, "--variant", "game",
            "--gen-limit", str(gen_limit), "--checkpoint-every", str(every),
            "--checkpoint-dir", ck, "--output", out]

    # SIGKILL while the background writer is mid-payload-write #2 (the
    # generation-12 payload): by then the deferred wait at boundary 12 has
    # committed generation 6, and 12 must never become visible.
    crash = _gol(base, extra_env={
        "GOL_FAULTS": "kill_during_ckpt_write=2,kill_mode=sigkill",
    }, check=False)
    if crash.returncode != -signal.SIGKILL:
        print(f"pipeline-smoke: expected SIGKILL death, rc={crash.returncode}\n"
              f"{crash.stdout}\n{crash.stderr}")
        return False
    if os.path.exists(out):
        print("pipeline-smoke: killed run left a final output file")
        return False
    names = sorted(os.listdir(ck))
    if "ckpt-00000006.manifest.json" not in names:
        print(f"pipeline-smoke: committed checkpoint 6 missing after kill: {names}")
        return False
    if "ckpt-00000012.manifest.json" in names:
        print(f"pipeline-smoke: torn checkpoint 12 became visible: {names}")
        return False
    for name in names:  # no committed manifest may dangle
        if name.endswith(".manifest.json"):
            with open(os.path.join(ck, name)) as f:
                payload = json.load(f)["payload"]
            if not os.path.exists(os.path.join(ck, payload)):
                print(f"pipeline-smoke: manifest {name} dangles ({payload})")
                return False

    resumed = _gol([*base, "--auto-resume"])
    res_gens = [l for l in resumed.stdout.splitlines()
                if l.startswith("Generations")]
    if open(out, "rb").read() != open(ref, "rb").read() or res_gens != ref_gens:
        print("pipeline-smoke: auto-resumed output diverges from the "
              "uninterrupted run")
        return False

    # A/B: the sync writer must produce byte-identical output AND payloads.
    ck_sync = os.path.join(workdir, "ck-sync")
    out_sync = os.path.join(workdir, "out-sync.out")
    _gol(["run", "64", "64", infile, "--variant", "game",
          "--gen-limit", str(gen_limit), "--checkpoint-every", str(every),
          "--checkpoint-dir", ck_sync, "--output", out_sync,
          "--sync-checkpoints", "--checkpoint-keep", "8"])
    ck_async = os.path.join(workdir, "ck-async")
    out_async = os.path.join(workdir, "out-async.out")
    _gol(["run", "64", "64", infile, "--variant", "game",
          "--gen-limit", str(gen_limit), "--checkpoint-every", str(every),
          "--checkpoint-dir", ck_async, "--output", out_async,
          "--checkpoint-keep", "8"])
    if open(out_sync, "rb").read() != open(out_async, "rb").read():
        print("pipeline-smoke: sync/async final outputs differ")
        return False
    for name in sorted(os.listdir(ck_sync)):
        if name.endswith(".out"):
            a = open(os.path.join(ck_sync, name), "rb").read()
            b = open(os.path.join(ck_async, name), "rb").read()
            if a != b:
                print(f"pipeline-smoke: payload {name} differs sync vs async")
                return False
    print("pipeline-smoke: checkpoint half OK — mid-write SIGKILL resumed "
          "byte-identically; sync/async payloads identical")
    return True


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(method, url, body=None, timeout=10):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def serve_half(workdir: str, jobs: int, gen_limit: int) -> bool:
    from gol_tpu.io import text_grid  # noqa: E402 - after sys.path insert

    journal_dir = os.path.join(workdir, "journal")
    port = _free_port()
    base = f"http://127.0.0.1:{port}"
    proc = subprocess.Popen(
        [sys.executable, "-m", "gol_tpu", "serve", "--port", str(port),
         "--journal-dir", journal_dir, "--flush-age", "0.05",
         "--pipeline-depth", "2"],
        env=_env(), cwd=ROOT, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    try:
        deadline = time.perf_counter() + 120
        while True:
            if proc.poll() is not None:
                out, _ = proc.communicate()
                print(f"pipeline-smoke: server died on boot rc="
                      f"{proc.returncode}:\n{out[-3000:]}")
                return False
            try:
                status, _ = _http("GET", f"{base}/healthz", timeout=2)
                if status == 200:
                    break
            except (urllib.error.URLError, OSError):
                pass
            if time.perf_counter() > deadline:
                print("pipeline-smoke: server never became healthy")
                return False
            time.sleep(0.1)

        accepted = set()
        for i in range(jobs):
            side = 32 if i % 2 == 0 else 30  # packed + masked buckets
            board = text_grid.generate(side, side, seed=2000 + i)
            status, payload = _http("POST", f"{base}/jobs", {
                "width": side, "height": side,
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": gen_limit,
            })
            if status != 202:
                print(f"pipeline-smoke: submit {i} rejected {status}: {payload}")
                return False
            accepted.add(payload["id"])

        pending = set(accepted)
        deadline = time.perf_counter() + 300
        while pending and time.perf_counter() < deadline:
            for job_id in list(pending):
                status, payload = _http("GET", f"{base}/jobs/{job_id}")
                if status != 200 or payload["state"] in ("failed", "cancelled"):
                    print(f"pipeline-smoke: job {job_id} -> {status} {payload}")
                    return False
                if payload["state"] == "done":
                    pending.discard(job_id)
            if pending:
                time.sleep(0.1)
        if pending:
            print(f"pipeline-smoke: {len(pending)} job(s) never completed")
            return False

        status, payload = _http("POST", f"{base}/drain", {}, timeout=60)
        if status != 200 or not payload.get("drained"):
            print(f"pipeline-smoke: drain failed {status}: {payload}")
            return False
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            print("pipeline-smoke: server ignored SIGTERM")
            proc.kill()
            return False

        # Exactly-once ledger: every accepted id has exactly one done record.
        done: dict = {}
        with open(os.path.join(journal_dir, "journal.jsonl"), "rb") as f:
            for line in f.read().split(b"\n"):
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("event") == "done":
                    done[rec["id"]] = done.get(rec["id"], 0) + 1
        lost = accepted - set(done)
        dup = {k: v for k, v in done.items() if v != 1}
        extra = set(done) - accepted
        if lost or dup or extra:
            print(f"pipeline-smoke: lost={lost} dup={dup} unknown={extra}")
            return False
        print(f"pipeline-smoke: serve half OK — {len(accepted)} jobs through "
              f"a depth-2 pipeline, drained clean, every job DONE exactly once")
        return True
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--jobs", type=int, default=24)
    parser.add_argument("--gen-limit", type=int, default=200)
    args = parser.parse_args(argv)

    workdir = tempfile.mkdtemp(prefix="gol-pipeline-smoke-")
    ok = False
    try:
        ok = checkpoint_half(workdir) and serve_half(
            workdir, args.jobs, args.gen_limit
        )
        print(f"pipeline-smoke: {'PASS' if ok else 'FAIL'}")
        return 0 if ok else 1
    finally:
        if ok:
            shutil.rmtree(workdir, ignore_errors=True)
        else:
            print(f"pipeline-smoke: artifacts kept in {workdir}")


if __name__ == "__main__":
    sys.exit(main())
