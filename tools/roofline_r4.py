"""Roofline measurements for benchmarks/roofline_r4.md (VERDICT r3 item 7).

Two experiments on the single-chip temporal kernel at 16384^2 and 65536^2:

1. FLAG COST A/B — the per-generation alive/similar flag computation
   (2 selects + 2 max-reduces + 1 xor over every band) is the only part of
   the per-word op budget not in the adder network itself. A variant kernel
   with the flag math deleted (returns constant flags — NOT a usable
   engine kernel, measurement only) bounds how much of the budget flags
   consume.
2. T=8 GHOST OVERFETCH — rates at two band sizes quantify the
   (band+16)/band overfetch share, pinning the HBM column of the roofline.

    python tools/roofline_r4.py   # -> benchmarks/roofline_flags_r4.json
"""

from __future__ import annotations

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.ops import packed_math
from gol_tpu.ops import stencil_packed as sp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "benchmarks", "roofline_flags_r4.json")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _force(x):
    int(np.asarray(x[0, 0]))


def _bandt_noflags_kernel(main_ref, top_ref, bot_ref, out_ref, *, band):
    """_bandt_kernel with the flag math deleted (measurement-only)."""
    x = jnp.concatenate([top_ref[:], main_ref[:], bot_ref[:]], axis=0)
    nwords = x.shape[1]
    for _ in range(sp.TEMPORAL_GENS):
        left = pltpu.roll(x, 1 % nwords, 1)
        right = pltpu.roll(x, (nwords - 1) % nwords, 1)
        m0, m1, s0, s1 = packed_math.row_sums(x, left, right)
        x = sp._vroll_combine(s0, s1, m0, m1, x)
    out_ref[:] = x[8 : band + 8]


@functools.partial(jax.jit, static_argnames=())
def _step_t_noflags(words):
    height, nwords = words.shape
    band = sp._pick_band(height, nwords, sp._bandt_target(height, nwords))
    nb = height // sp._SUBLANES
    return pl.pallas_call(
        functools.partial(_bandt_noflags_kernel, band=band),
        grid=(height // band,),
        in_specs=sp._banded_specs(band, nwords, nb),
        out_specs=pl.BlockSpec((band, nwords), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((height, nwords), jnp.uint32),
        compiler_params=pltpu.CompilerParams(dimension_semantics=("arbitrary",)),
    )(words, words, words)


def _rate(step, words, n, size):
    """Cells/s from DEVICE time — wall-clock marginals over the attach
    tunnel go negative between drift spikes; device time was repeatable to
    3 decimals across sessions (benchmarks/compare_*_r4). Shares
    measure_r4's trace->op_profile extraction (incl. its cleanup and
    error tolerance)."""
    from tools.measure_r4 import _device_time_per_pass

    fn = jax.jit(lambda w, m: jax.lax.fori_loop(0, m, lambda i, x: step(x), w),
                 static_argnums=1)
    _force(fn(words, 2))
    ms = _device_time_per_pass(fn, words, n)
    if ms is None:
        raise RuntimeError("device-time extraction unavailable (xprof)")
    return size * size * sp.TEMPORAL_GENS / (ms / 1000.0)


def main() -> None:
    assert jax.default_backend() == "tpu"
    results = {}
    for size, n in ((16384, 50), (65536, 10)):
        rng = np.random.default_rng(42)
        grid = rng.integers(0, 2, size=(size, size), dtype=np.uint8)
        words = jnp.asarray(
            np.packbits(grid, axis=1, bitorder="little").view(np.uint32))
        flags, noflags = [], []
        for rep in range(3):
            flags.append(_rate(lambda w: sp._step_t(w)[0], words, n, size))
            noflags.append(_rate(_step_t_noflags, words, n, size))
            log(f"{size}: rep {rep} flags={flags[-1]/1e12:.3f}T "
                f"noflags={noflags[-1]/1e12:.3f}T")
        fm = sorted(flags)[1]
        nm = sorted(noflags)[1]
        results[str(size)] = {
            "with_flags_cells_per_s": [round(r) for r in flags],
            "no_flags_cells_per_s": [round(r) for r in noflags],
            "flag_overhead_fraction": round(nm / fm - 1, 4),
        }
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)
            f.write("\n")
    log("wrote", OUT)


if __name__ == "__main__":
    main()
