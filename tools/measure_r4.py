"""Thin shim: the r4 measurement battery lives in tools/measure.py (--rev 4).

Kept so documented commands (`python tools/measure_r4.py compare 16384` etc.)
keep working — artifacts still land as *_r4.json; the argument mapping lives
in measure.py's ``_SHIM_ARGS`` table.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from measure import shim_main  # noqa: E402

if __name__ == "__main__":
    sys.exit(shim_main(__file__))
