"""Macrocell deep-time smoke: 10^6 generations + census + warm-CAS restart.

The `make macro-smoke` harness, exercising ISSUE 17's end-to-end
acceptance behaviors:

1. **Gosper gun to 10^6 generations** — the macro engine runs the gun a
   MILLION generations in a 2^20-cell-per-side universe (a board no
   per-generation engine could touch in smoke time) and the resulting
   population must match the closed-form glider census: the gun emits
   one 5-cell glider every 30 generations, and on a plane nothing ever
   collides, so for any two generations with the same period-30 phase,
   ``pop(g) = pop(g0) + 5 * (g - g0) / 30``. The anchor ``pop(g0)`` is
   measured by the per-generation sparse engine at a shallow g0 with
   ``g0 ≡ 10^6 (mod 30)`` — so the tree's answer at depth 10^6 is gated
   by an independent engine plus arithmetic, not by another tree run.

2. **Restart hits the warm CAS** — a second run of the same question
   from a FRESH node store and memo (everything process-local discarded;
   only the CAS directory survives, the restart shape) must serve
   content-tier hits and finish with strictly less device work.

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/macro_smoke.py
"""

import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

UNIVERSE = 1 << 20
TILE = 256
GENS = 1_000_000
# Same period-30 phase as GENS (10^6 ≡ 10 ≡ 40 mod 30), deep enough that
# the gun has started emitting.
ANCHOR_GENS = 40


def fail(msg: str) -> None:
    print(f"MACRO-SMOKE FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def _gun_rle() -> str:
    with open(os.path.join(REPO, "patterns", "gosper_gun.rle"),
              encoding="utf-8") as f:
        return f.read()


def _board(universe: int, tile: int):
    from gol_tpu.sparse import SparseBoard

    at = universe // 2
    return SparseBoard.from_rle(_gun_rle(), universe, universe, tile,
                                x=at, y=at)


def main() -> int:
    from gol_tpu.config import GameConfig
    from gol_tpu.macro import MacroMemo, NodeStore, simulate_macro
    from gol_tpu.sparse import simulate_sparse

    assert GENS % 30 == ANCHOR_GENS % 30, "census anchor must share phase"

    # The census anchor, from the independent per-generation engine.
    anchor = simulate_sparse(_board(8192, TILE),
                             GameConfig(gen_limit=ANCHOR_GENS))
    expected = (anchor.board.population()
                + 5 * (GENS - ANCHOR_GENS) // 30)

    cas_dir = tempfile.mkdtemp(prefix="macro_smoke_cas_")
    try:
        memo = MacroMemo(NodeStore(TILE), cas_dir=cas_dir)
        t0 = time.perf_counter()
        cold = simulate_macro(_board(UNIVERSE, TILE),
                              GameConfig(gen_limit=GENS), memo)
        cold_s = time.perf_counter() - t0
        if cold.generations != GENS or cold.exit_reason != "gen_limit":
            fail(f"cold run ended ({cold.generations}, {cold.exit_reason}),"
                 f" want ({GENS}, gen_limit)")
        pop = cold.board.population()
        if pop != expected:
            fail(f"census mismatch at {GENS} generations: population {pop},"
                 f" closed form {expected} (anchor "
                 f"{anchor.board.population()} at {ANCHOR_GENS})")
        print(
            f"  census gate: {GENS} generations in {cold_s:.1f}s, "
            f"population {pop} == {anchor.board.population()} + "
            f"5*({GENS}-{ANCHOR_GENS})/30 "
            f"({cold.stats.supersteps} supersteps, "
            f"{cold.stats.leaf_gen_steps} leaf device steps)",
            file=sys.stderr,
        )

        # Restart: fresh store + memo, same CAS directory.
        memo2 = MacroMemo(NodeStore(TILE), cas_dir=cas_dir)
        t0 = time.perf_counter()
        warm = simulate_macro(_board(UNIVERSE, TILE),
                              GameConfig(gen_limit=GENS), memo2)
        warm_s = time.perf_counter() - t0
        if warm.board.population() != pop:
            fail(f"warm rerun diverged: population "
                 f"{warm.board.population()} vs {pop}")
        if warm.stats.cas_hits == 0:
            fail("restart run served 0 CAS hits — the content tier did "
                 "not survive the restart")
        if warm.stats.leaf_gen_steps >= cold.stats.leaf_gen_steps:
            fail(f"restart run did {warm.stats.leaf_gen_steps} leaf device"
                 f" steps, not less than the cold run's "
                 f"{cold.stats.leaf_gen_steps}")
        print(
            f"  restart gate: warm CAS rerun in {warm_s:.1f}s "
            f"({warm.stats.cas_hits} content hits, "
            f"{warm.stats.leaf_gen_steps} vs {cold.stats.leaf_gen_steps} "
            f"leaf device steps)",
            file=sys.stderr,
        )
    finally:
        shutil.rmtree(cas_dir, ignore_errors=True)

    print("MACRO-SMOKE PASS", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
