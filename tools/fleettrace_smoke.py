"""Fleet-tracing + metrics-history smoke: one stitched timeline, one
durable record, through a real worker kill.

The `make fleettrace-smoke` harness, exercising the ISSUE 10 acceptance
end-to-end against real OS processes:

1. boot ``gol fleet --workers 2`` with ``--trace`` (router + every worker
   armed, X-Gol-Trace stamped on forwards), ``--result-cache`` and
   ``--metrics-history`` (per-partition worker rings + the router's
   merged, respawn-floored ring);
2. submit a Zipf-shaped load — a few unique boards across two bucket
   shapes, the head board submitted over and over — so the cache tier
   serves real hits while the engine lanes stay busy;
3. SIGKILL one worker mid-load (forcing at least one spillover-routed
   submit while it is down) and keep submitting; the health loop respawns
   it on its partition;
4. wait for every accepted job to be DONE through the router, then run
   ``gol fleet-trace``: the output must be ONE valid Chrome/Perfetto JSON
   containing the router and BOTH live workers (>= 2 distinct worker
   pids) and at least one cross-process flow chain (one flow id with
   points in the router pid AND a worker pid);
5. ``gol history-report`` must render the router's ring, and the merged
   ``jobs_completed_total`` series in it must be MONOTONIC across the
   kill/respawn window (the PR-8 floors, made durable).

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/fleettrace_smoke.py [--jobs 40] [--gen-limit 150]
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gol_tpu.io import text_grid  # noqa: E402
from gol_tpu.obs import history  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _start_fleet(port: int, fleet_dir: str, trace_dir: str):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [
            sys.executable, "-m", "gol_tpu", "fleet",
            "--port", str(port),
            "--workers", "2",
            "--fleet-dir", fleet_dir,
            "--trace", trace_dir,
            "--metrics-history",
            "--result-cache",
            "--flush-age", "0.05",
            "--max-batch", "8",
            "--health-interval", "0.5",
            "--sample-interval", "0.25",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )


def _await_healthy(base: str, timeout: float = 240.0) -> None:
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        try:
            status, payload = _http("GET", f"{base}/healthz", timeout=3)
            if status == 200 and payload.get("ok"):
                return
        except (urllib.error.URLError, ConnectionError, OSError):
            pass
        time.sleep(0.25)
    raise RuntimeError("fleet router never became healthy")


def _submit(base, board, gen_limit, attempts=40):
    body = {
        "width": board.shape[1], "height": board.shape[0],
        "cells": text_grid.encode(board).decode("ascii"),
        "gen_limit": gen_limit,
    }
    last = None
    for _ in range(attempts):
        try:
            status, payload = _http("POST", f"{base}/jobs", body, timeout=60)
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            last = f"{type(e).__name__}: {e}"
            time.sleep(0.25)
            continue
        if status == 202:
            return payload["id"]
        last = f"HTTP {status}: {payload}"
        time.sleep(0.25)  # 429/503/504 during the kill window: retry
    raise RuntimeError(f"submit never accepted: {last}")


def _cli(args, timeout=120):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "gol_tpu", *args],
        env=env, capture_output=True, text=True, timeout=timeout,
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--jobs", type=int, default=40)
    parser.add_argument("--gen-limit", type=int, default=150)
    args = parser.parse_args(argv)

    workdir = tempfile.mkdtemp(prefix="gol-fleettrace-smoke-")
    fleet_dir = os.path.join(workdir, "fleet")
    trace_dir = os.path.join(workdir, "trace")
    port = _free_port()
    base = f"http://127.0.0.1:{port}"
    proc = _start_fleet(port, fleet_dir, trace_dir)
    rc = 1
    try:
        _await_healthy(base)
        print(f"fleettrace-smoke: fleet up at {base}", flush=True)

        # The Zipf-ish load: 6 unique boards over two bucket shapes; the
        # head board repeats (cache hits once --result-cache has it).
        uniques = [text_grid.generate(32 if i % 2 == 0 else 30,
                                      32 if i % 2 == 0 else 30,
                                      seed=7000 + i)
                   for i in range(6)]
        order = [uniques[0], uniques[1], uniques[2], uniques[0],
                 uniques[3], uniques[0], uniques[4], uniques[1],
                 uniques[0], uniques[5]]
        ids = []
        kill_at = args.jobs // 2
        victim = None
        for i in range(args.jobs):
            ids.append(_submit(base, order[i % len(order)], args.gen_limit))
            if i + 1 == kill_at:
                # SIGKILL the busiest worker mid-load: submits that rank
                # it first must spill to the survivor until the health
                # loop respawns the partition.
                _, membership = _http("GET", f"{base}/fleet")
                workers = membership["workers"]
                victim = workers[0]
                print(f"fleettrace-smoke: SIGKILL worker {victim['id']} "
                      f"(pid {victim['pid']})", flush=True)
                os.kill(victim["pid"], signal.SIGKILL)

        deadline = time.perf_counter() + 300
        pending = set(ids)
        while pending and time.perf_counter() < deadline:
            for job_id in list(pending):
                try:
                    status, payload = _http("GET", f"{base}/jobs/{job_id}",
                                            timeout=10)
                except (urllib.error.URLError, ConnectionError, OSError):
                    break
                if status == 200 and payload.get("state") == "done":
                    pending.discard(job_id)
                elif status == 200 and payload.get("state") in (
                    "failed", "cancelled"
                ):
                    print(f"fleettrace-smoke: FAIL job {job_id} ended "
                          f"{payload['state']}")
                    return 1
            time.sleep(0.2)
        if pending:
            print(f"fleettrace-smoke: FAIL {len(pending)} job(s) never "
                  "finished")
            return 1
        print(f"fleettrace-smoke: all {len(ids)} jobs DONE through the "
              "kill/respawn", flush=True)

        # Respawn must have landed (same partition, new pid) before the
        # stitch expects two live workers.
        deadline = time.perf_counter() + 120
        while time.perf_counter() < deadline:
            _, membership = _http("GET", f"{base}/fleet")
            live = [w for w in membership["workers"]
                    if w.get("healthy") and w.get("url")]
            if len(live) >= 2:
                break
            time.sleep(0.25)
        else:
            print("fleettrace-smoke: FAIL respawn never became healthy")
            return 1

        # --- gol fleet-trace: ONE valid Perfetto JSON -------------------
        out_path = os.path.join(workdir, "fleet-trace.json")
        result = _cli(["fleet-trace", "--server", base, "-o", out_path])
        if result.returncode != 0:
            print("fleettrace-smoke: FAIL gol fleet-trace rc="
                  f"{result.returncode}\n{result.stdout}\n{result.stderr}")
            return 1
        with open(out_path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        events = doc["traceEvents"]
        procs = doc["otherData"]["processes"]
        router_pids = {info["pid"] for name, info in procs.items()
                       if name == "router"}
        worker_pids = {info["pid"] for name, info in procs.items()
                       if name != "router"}
        if not router_pids or len(worker_pids) < 2:
            print(f"fleettrace-smoke: FAIL process table {procs}")
            return 1
        real_worker_pids = {info["real_pid"] for name, info in procs.items()
                           if name != "router"}
        if len(real_worker_pids) < 2:
            print(f"fleettrace-smoke: FAIL wanted >= 2 worker pids, got "
                  f"{real_worker_pids}")
            return 1
        flows = {}
        for e in events:
            if e.get("ph") in ("s", "t", "f"):
                flows.setdefault(e["id"], set()).add(e["pid"])
        chains = [fid for fid, pids in flows.items()
                  if pids & router_pids and pids & worker_pids]
        if not chains:
            print(f"fleettrace-smoke: FAIL no cross-process flow chain "
                  f"(flows: {dict(list(flows.items())[:5])})")
            return 1
        spans = [e for e in events if e.get("ph") == "X"]
        if not spans:
            print("fleettrace-smoke: FAIL stitched trace has no spans")
            return 1
        print(f"fleettrace-smoke: stitched {len(procs)} processes, "
              f"{len(spans)} spans, {len(chains)} cross-process flow "
              f"chain(s)", flush=True)

        # --- durable history: monotonic through the respawn -------------
        router_history = os.path.join(fleet_dir, "router-history")
        series = history.counter_series(router_history,
                                        "jobs_completed_total")
        values = [v for run in series for _, v in run]
        if len(values) < 3:
            print(f"fleettrace-smoke: FAIL router history too thin "
                  f"({len(values)} samples)")
            return 1
        if values != sorted(values):
            print("fleettrace-smoke: FAIL merged jobs_completed_total "
                  f"dipped across the respawn: {values}")
            return 1
        if values[-1] < len(ids):
            print(f"fleettrace-smoke: FAIL merged total {values[-1]} < "
                  f"{len(ids)} accepted jobs")
            return 1
        result = _cli(["history-report", router_history])
        if result.returncode != 0 or "jobs_completed_total" not in result.stdout:
            print("fleettrace-smoke: FAIL gol history-report rc="
                  f"{result.returncode}\n{result.stdout}\n{result.stderr}")
            return 1
        # Worker partitions wrote their own rings too.
        worker_rings = [d for d in (os.path.join(fleet_dir, w, "history")
                                    for w in ("w0", "w1"))
                        if os.path.isdir(d) and history.runs(d)]
        if not worker_rings:
            print("fleettrace-smoke: FAIL no worker partition history ring")
            return 1
        print(f"fleettrace-smoke: history monotonic over {len(values)} "
              f"samples (final total {values[-1]}); "
              f"{len(worker_rings)} worker ring(s)", flush=True)
        rc = 0
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)
            if rc == 0:
                print("fleettrace-smoke: FAIL fleet ignored SIGTERM")
                rc = 1
        if rc == 0 and proc.returncode != 0:
            out = proc.stdout.read().decode("utf-8", "replace")[-3000:]
            print(f"fleettrace-smoke: FAIL fleet exited rc="
                  f"{proc.returncode}\n{out}")
            rc = 1
        shutil.rmtree(workdir, ignore_errors=True)
    if rc == 0:
        print("fleettrace-smoke: PASS — one stitched Perfetto timeline "
              "(router + 2 workers, cross-process flows) and a monotonic "
              "durable history through a worker SIGKILL/respawn")
    return rc


if __name__ == "__main__":
    sys.exit(main())
