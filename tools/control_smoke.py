"""Control-plane failover smoke: SIGKILL the lease-holding router,
verify the survivor takes over with zero lost work.

The `make control-smoke` harness, exercising the horizontal control
plane end-to-end against real OS processes:

1. boot ``gol fleet --workers 2 --routers 2`` on a fresh ``--fleet-dir``
   (the primary router ``r0`` holds the leader flock; replica ``r1``
   boots from the shared manifest and advertises its URL under
   ``<fleet-dir>/routers/r1/advert.json``);
2. submit the first half of the load ALTERNATING between both routers —
   any replica must place and forward, not just the leader;
3. SIGKILL the lease-holding router (``r0``, the ``gol fleet`` process
   itself) while jobs are in flight: the kernel drops its flock, and
   the surviving replica's next health tick must win the lease and
   report ``leader: true`` on ``/healthz``;
4. SIGKILL a worker that accepted work: the SURVIVOR's health loop must
   detect and respawn it on the same partition (supervision ticks
   transferred with the lease, not just the label);
5. submit the second half of the load through the survivor, then wait
   until every accepted job reports DONE through it;
6. verify every result against the NumPy oracle (byte-identical through
   both kills);
7. SIGTERM the survivor and the workers, then audit across ALL
   partition journals that every accepted id has EXACTLY one done
   record fleet-wide (none lost, none double-run through the router
   handoff).

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/control_smoke.py [--jobs 60] [--gen-limit 300]
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from gol_tpu import oracle  # noqa: E402
from gol_tpu.config import GameConfig  # noqa: E402
from gol_tpu.io import text_grid  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _start_fleet(port: int, fleet_dir: str):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "gol_tpu", "fleet",
            "--port", str(port),
            "--workers", "2",
            "--routers", "2",
            "--fleet-dir", fleet_dir,
            "--flush-age", "0.05",
            "--health-interval", "0.5",
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    deadline = time.perf_counter() + 300
    base = f"http://127.0.0.1:{port}"
    while time.perf_counter() < deadline:
        if proc.poll() is not None:
            out, _ = proc.communicate()
            raise RuntimeError(
                f"fleet died on boot rc={proc.returncode}:\n{out[-4000:]}"
            )
        try:
            status, payload = _http("GET", f"{base}/healthz", timeout=2)
            if (status == 200 and payload.get("leader")
                    and payload.get("fleet", {}).get("workers") == 2):
                return proc
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.2)
    proc.kill()
    raise RuntimeError("fleet did not become healthy within 300s")


def _wait_replica(fleet_dir: str, rid: str, timeout: float = 120):
    """Wait for the replica's advert + a live /healthz; return (url, pid)."""
    advert_path = os.path.join(fleet_dir, "routers", rid, "advert.json")
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        try:
            with open(advert_path, encoding="utf-8") as f:
                advert = json.load(f)
            status, payload = _http(
                "GET", f"{advert['url']}/healthz", timeout=2)
            if status == 200 and payload.get("id") == rid:
                return advert["url"], advert["pid"]
        except (OSError, ValueError, KeyError):
            pass
        time.sleep(0.2)
    raise RuntimeError(f"replica {rid} never advertised a live /healthz")


def _fleet_workers(base: str) -> list:
    status, payload = _http("GET", f"{base}/fleet")
    if status != 200:
        raise RuntimeError(f"GET /fleet -> {status}: {payload}")
    return payload["workers"]


def _count_done(fleet_dir: str) -> dict:
    """id -> [(partition, record)] across every partition journal."""
    from gol_tpu.serve import compaction

    done: dict = {}
    for name in sorted(os.listdir(fleet_dir)):
        part = os.path.join(fleet_dir, name)
        if not os.path.isfile(os.path.join(part, "journal.jsonl")):
            continue
        for rec in compaction.iter_records(part):
            if rec.get("event") == "done":
                done.setdefault(rec["id"], []).append((name, rec))
    return done


def _term_and_wait(pid: int, label: str, timeout: float = 60) -> bool:
    try:
        os.kill(pid, signal.SIGTERM)
    except ProcessLookupError:
        return True
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return True
        time.sleep(0.1)
    print(f"control-smoke: {label} pid {pid} ignored SIGTERM")
    try:
        os.kill(pid, signal.SIGKILL)
    except ProcessLookupError:
        pass
    return False


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--jobs", type=int, default=60)
    parser.add_argument("--gen-limit", type=int, default=300)
    args = parser.parse_args(argv)

    workdir = tempfile.mkdtemp(prefix="gol-control-smoke-")
    fleet_dir = os.path.join(workdir, "fleet")
    port = _free_port()
    r0_url = f"http://127.0.0.1:{port}"
    cfg = GameConfig(gen_limit=args.gen_limit)
    sides = (32, 30)

    rc = 1
    proc = None
    cleanup_pids: list = []
    try:
        proc = _start_fleet(port, fleet_dir)
        r1_url, r1_pid = _wait_replica(fleet_dir, "r1")
        cleanup_pids.append(("replica r1", r1_pid))
        print(f"control-smoke: 2-router fleet up — r0 {r0_url} (leader), "
              f"r1 {r1_url}")

        # First half of the load, alternating routers: ANY replica places.
        accepted = {}  # id -> (board, router that accepted it)
        half = args.jobs // 2
        taken_by = {"r0": 0, "r1": 0}
        for i in range(half):
            side = sides[i % 2]
            board = text_grid.generate(side, side, seed=7000 + i)
            rid, base = ("r0", r0_url) if i % 2 == 0 else ("r1", r1_url)
            status, payload = _http("POST", f"{base}/jobs", {
                "width": side, "height": side,
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": args.gen_limit,
            })
            if status != 202:
                print(f"control-smoke: submit {i} via {rid} rejected "
                      f"HTTP {status}: {payload}")
                return 1
            accepted[payload["id"]] = board
            taken_by[rid] += 1
        if not (taken_by["r0"] and taken_by["r1"]):
            print(f"control-smoke: expected both routers to accept work, "
                  f"got {taken_by}")
            return 1
        print(f"control-smoke: {half} jobs accepted ({taken_by}); "
              f"SIGKILL leader r0 (pid {proc.pid}) mid-load")

        # Kill the lease holder with jobs in flight. The kernel drops its
        # flock; r1's next health tick must win the lease.
        cleanup_pids.extend(
            ("worker " + w["id"], w["pid"])
            for w in _fleet_workers(r1_url) if w.get("pid"))
        os.kill(proc.pid, signal.SIGKILL)
        proc.communicate()
        proc = None

        deadline = time.perf_counter() + 120
        took_over = False
        while time.perf_counter() < deadline:
            try:
                status, payload = _http("GET", f"{r1_url}/healthz", timeout=2)
                if status == 200 and payload.get("leader"):
                    took_over = True
                    break
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.2)
        if not took_over:
            print("control-smoke: r1 never took the lease after r0's "
                  "SIGKILL")
            return 1
        print("control-smoke: survivor r1 holds the lease")

        # Supervision moved with the lease: SIGKILL a worker, the SURVIVOR
        # must respawn it on the same partition.
        victim = _fleet_workers(r1_url)[0]
        print(f"control-smoke: SIGKILL worker {victim['id']} "
              f"(pid {victim['pid']}) under the survivor's watch")
        os.kill(victim["pid"], signal.SIGKILL)
        deadline = time.perf_counter() + 300
        respawned = False
        while time.perf_counter() < deadline:
            try:
                workers = _fleet_workers(r1_url)
            except (RuntimeError, urllib.error.URLError, OSError):
                time.sleep(0.2)
                continue
            mine = next((w for w in workers if w["id"] == victim["id"]), None)
            if mine and mine.get("healthy") and mine.get("restarts", 0) >= 1:
                respawned = True
                cleanup_pids.append(("worker " + mine["id"], mine["pid"]))
                break
            time.sleep(0.2)
        if not respawned:
            print("control-smoke: survivor never respawned the killed "
                  "worker — supervision ticks did not transfer")
            return 1
        print("control-smoke: survivor respawned the worker "
              "(ticks continue)")

        # Second half of the load through the survivor alone.
        for i in range(half, args.jobs):
            side = sides[i % 2]
            board = text_grid.generate(side, side, seed=7000 + i)
            status, payload = _http("POST", f"{r1_url}/jobs", {
                "width": side, "height": side,
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": args.gen_limit,
            })
            if status != 202:
                print(f"control-smoke: post-failover submit {i} rejected "
                      f"HTTP {status}: {payload}")
                return 1
            accepted[payload["id"]] = board

        # Every accepted job must reach DONE through the survivor.
        deadline = time.perf_counter() + 600
        pending = set(accepted)
        while pending and time.perf_counter() < deadline:
            for job_id in list(pending):
                try:
                    status, payload = _http(
                        "GET", f"{r1_url}/jobs/{job_id}", timeout=10)
                except (urllib.error.URLError, OSError):
                    break
                if status >= 500:
                    continue  # respawn window; keep polling
                if status != 200:
                    print(f"control-smoke: job {job_id} LOST "
                          f"(HTTP {status}: {payload})")
                    return 1
                state = payload["state"]
                if state == "done":
                    pending.discard(job_id)
                elif state in ("failed", "cancelled"):
                    print(f"control-smoke: job {job_id} ended {state}: "
                          f"{payload}")
                    return 1
            if pending:
                time.sleep(0.2)
        if pending:
            print(f"control-smoke: {len(pending)} job(s) never completed")
            return 1
        print(f"control-smoke: all {len(accepted)} jobs DONE through "
              "both kills")

        # Results byte-identical to the oracle, fetched via the survivor.
        for job_id, board in accepted.items():
            status, result = _http("GET", f"{r1_url}/result/{job_id}")
            if status != 200:
                print(f"control-smoke: result {job_id} HTTP {status}")
                return 1
            want = oracle.run(board, cfg)
            got = text_grid.decode(
                result["grid"].encode("ascii"),
                result["width"], result["height"],
            )
            if (not np.array_equal(np.asarray(got), want.grid)
                    or result["generations"] != want.generations):
                print(f"control-smoke: result {job_id} diverges from the "
                      "oracle")
                return 1
        print("control-smoke: every result oracle-identical")

        # Orderly teardown: the survivor first (cascade=False — workers
        # outlive any one router), then each worker.
        for label, pid in cleanup_pids:
            _term_and_wait(pid, label)
        cleanup_pids = []

        done = _count_done(fleet_dir)
        lost = set(accepted) - set(done)
        extra = set(done) - set(accepted)
        dup = {k: [p for p, _ in v] for k, v in done.items() if len(v) != 1}
        if lost or extra or dup:
            print(f"control-smoke: lost={lost} unknown={extra} "
                  f"duplicated={dup}")
            return 1
        print(
            f"control-smoke: PASS — {len(accepted)} jobs exactly-once "
            "through a leader SIGKILL (lease transferred, ticks continued, "
            "worker respawned by the survivor), results oracle-identical"
        )
        rc = 0
        return 0
    finally:
        for _, pid in cleanup_pids:
            try:
                os.kill(pid, signal.SIGKILL)
            except (ProcessLookupError, OSError):
                pass
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.communicate()
        if rc == 0:
            shutil.rmtree(workdir, ignore_errors=True)
        else:
            print(f"control-smoke: artifacts kept in {workdir}")


if __name__ == "__main__":
    sys.exit(main())
