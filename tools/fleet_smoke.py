"""Fleet crash/rebalance smoke: kill a worker mid-batch, verify exactly-once.

The `make fleet-smoke` harness, exercising the sharded-fleet acceptance
end-to-end against real OS processes:

1. boot ``gol fleet --workers 3`` on a fresh ``--fleet-dir`` (3 journal
   partitions + the membership manifest);
2. submit N jobs (default 100) across THREE bucket shapes (32x32 exact-fit
   packed, 30x30 masked, 64x64 packed) through the router — every accepted
   id is remembered along with the worker that took it;
3. SIGKILL one worker that accepted work, while work is in flight: the
   router's health loop must detect it, respawn it on the SAME partition,
   and its journal must replay the partition's unfinished jobs (new jobs
   spill to other workers in the meantime — the rebalance lane);
4. wait until every accepted job reports DONE through the router;
5. verify every result against the NumPy oracle (byte-identical to a solo
   run, through the kill);
6. SIGTERM the fleet process: the cascaded graceful drain must complete,
   every worker process must exit, and the fleet must exit rc 0;
7. verify across ALL partition journals that every accepted id has EXACTLY
   one done record fleet-wide (none lost, none double-run, no partition
   holds a duplicate of another's).

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/fleet_smoke.py [--jobs 100] [--gen-limit 300]
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from gol_tpu import oracle  # noqa: E402
from gol_tpu.config import GameConfig  # noqa: E402
from gol_tpu.io import text_grid  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _start_fleet(port: int, fleet_dir: str, workers: int = 3):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "gol_tpu", "fleet",
            "--port", str(port),
            "--workers", str(workers),
            "--fleet-dir", fleet_dir,
            "--flush-age", "0.05",
            "--health-interval", "0.5",
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    deadline = time.perf_counter() + 300
    base = f"http://127.0.0.1:{port}"
    while time.perf_counter() < deadline:
        if proc.poll() is not None:
            out, _ = proc.communicate()
            raise RuntimeError(
                f"fleet died on boot rc={proc.returncode}:\n{out[-4000:]}"
            )
        try:
            status, payload = _http("GET", f"{base}/healthz", timeout=2)
            if status == 200 and payload.get("fleet", {}).get("workers") == 3:
                return proc
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.2)
    proc.kill()
    raise RuntimeError("fleet did not become healthy within 300s")


def _fleet_workers(base: str) -> list:
    status, payload = _http("GET", f"{base}/fleet")
    if status != 200:
        raise RuntimeError(f"GET /fleet -> {status}: {payload}")
    return payload["workers"]


def _count_done(fleet_dir: str) -> dict:
    """id -> [(partition, record)] across every partition journal —
    enumerated via compaction.iter_records (snapshot + sealed segments +
    live file), so the audit survives journal rotation/compaction."""
    from gol_tpu.serve import compaction

    done: dict = {}
    for name in sorted(os.listdir(fleet_dir)):
        part = os.path.join(fleet_dir, name)
        if not os.path.isfile(os.path.join(part, "journal.jsonl")):
            continue
        for rec in compaction.iter_records(part):
            if rec.get("event") == "done":
                done.setdefault(rec["id"], []).append((name, rec))
    return done


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--jobs", type=int, default=100)
    parser.add_argument("--gen-limit", type=int, default=300)
    parser.add_argument(
        "--kill-after", type=float, default=0.8,
        help="seconds after the last submit to SIGKILL the victim worker",
    )
    args = parser.parse_args(argv)

    workdir = tempfile.mkdtemp(prefix="gol-fleet-smoke-")
    fleet_dir = os.path.join(workdir, "fleet")
    port = _free_port()
    base = f"http://127.0.0.1:{port}"
    cfg = GameConfig(gen_limit=args.gen_limit)
    sides = (32, 30, 64)  # 3 buckets: exact-fit packed, masked, bigger packed

    rc = 1
    proc = None
    try:
        proc = _start_fleet(port, fleet_dir)
        print(f"fleet-smoke: 3-worker fleet up on {base}, dir {fleet_dir}")

        accepted = {}  # id -> (board, worker_id)
        for i in range(args.jobs):
            side = sides[i % 3]
            board = text_grid.generate(side, side, seed=2000 + i)
            status, payload = _http("POST", f"{base}/jobs", {
                "width": side, "height": side,
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": args.gen_limit,
            })
            if status != 202:
                print(f"fleet-smoke: submit {i} rejected HTTP {status}: "
                      f"{payload}")
                return 1
            accepted[payload["id"]] = (board, payload.get("worker"))
        by_worker: dict = {}
        for _, (_, wid) in accepted.items():
            by_worker[wid] = by_worker.get(wid, 0) + 1
        print(f"fleet-smoke: accepted {len(accepted)} jobs across 3 buckets; "
              f"placement {by_worker}")

        # Pick a victim that actually took work, and SIGKILL it mid-batch.
        time.sleep(args.kill_after)
        victim_id = max(by_worker, key=lambda k: by_worker[k])
        victim = next(w for w in _fleet_workers(base)
                      if w["id"] == victim_id)
        print(f"fleet-smoke: SIGKILL worker {victim['id']} "
              f"(pid {victim['pid']}, {by_worker[victim_id]} jobs placed)")
        os.kill(victim["pid"], signal.SIGKILL)

        # Every accepted job must reach DONE through the router — the
        # victim's partition replays after the health loop respawns it.
        deadline = time.perf_counter() + 600
        pending = set(accepted)
        while pending and time.perf_counter() < deadline:
            for job_id in list(pending):
                try:
                    status, payload = _http("GET", f"{base}/jobs/{job_id}",
                                            timeout=10)
                except (urllib.error.URLError, OSError):
                    break  # router busy; retry the sweep
                if status >= 500:
                    continue  # the respawn window; keep polling
                if status != 200:
                    print(f"fleet-smoke: job {job_id} LOST "
                          f"(HTTP {status}: {payload})")
                    return 1
                state = payload["state"]
                if state == "done":
                    pending.discard(job_id)
                elif state in ("failed", "cancelled"):
                    print(f"fleet-smoke: job {job_id} ended {state}: "
                          f"{payload}")
                    return 1
            if pending:
                time.sleep(0.2)
        if pending:
            print(f"fleet-smoke: {len(pending)} job(s) never completed")
            return 1

        # The respawn must be visible in the membership (restarts >= 1).
        workers = _fleet_workers(base)
        restarts = sum(w["restarts"] for w in workers)
        if restarts < 1:
            print(f"fleet-smoke: expected a respawned worker, saw none: "
                  f"{workers}")
            return 1
        print(f"fleet-smoke: all jobs DONE through the kill "
              f"({restarts} worker restart(s))")

        # Results byte-identical to the oracle, fetched through the router.
        mismatches = 0
        for job_id, (board, _) in accepted.items():
            status, result = _http("GET", f"{base}/result/{job_id}")
            if status != 200:
                print(f"fleet-smoke: result {job_id} HTTP {status}")
                return 1
            want = oracle.run(board, cfg)
            got = text_grid.decode(
                result["grid"].encode("ascii"),
                result["width"], result["height"],
            )
            if (not np.array_equal(np.asarray(got), want.grid)
                    or result["generations"] != want.generations):
                mismatches += 1
        if mismatches:
            print(f"fleet-smoke: {mismatches} result(s) diverge from the "
                  "oracle")
            return 1
        print("fleet-smoke: every result oracle-identical")

        # Cascaded graceful drain: SIGTERM the fleet; it must drain every
        # worker, stop them, and exit 0; every worker pid must be gone.
        pids = [w["pid"] for w in workers if w["pid"]]
        proc.send_signal(signal.SIGTERM)
        try:
            out, _ = proc.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            print("fleet-smoke: fleet ignored SIGTERM")
            proc.kill()
            return 1
        if proc.returncode != 0:
            print(f"fleet-smoke: fleet exited rc={proc.returncode}:\n"
                  f"{out[-3000:]}")
            return 1
        proc = None
        for pid in pids:
            try:
                os.kill(pid, 0)
                print(f"fleet-smoke: worker pid {pid} survived the drain")
                return 1
            except ProcessLookupError:
                pass
        print("fleet-smoke: cascaded SIGTERM drain exited clean, "
              "all workers stopped")

        # Fleet-wide exactly-once: every accepted id has exactly one done
        # record across ALL partitions.
        done = _count_done(fleet_dir)
        lost = set(accepted) - set(done)
        extra = set(done) - set(accepted)
        dup = {k: [p for p, _ in v] for k, v in done.items() if len(v) != 1}
        if lost or extra or dup:
            print(f"fleet-smoke: lost={lost} unknown={extra} "
                  f"duplicated={dup}")
            return 1
        print(
            f"fleet-smoke: PASS — {len(accepted)} accepted across "
            f"{len({p for v in done.values() for p, _ in v})} partitions, "
            "worker SIGKILL replayed/rebalanced to exactly-once, results "
            "oracle-identical, cascaded drain clean"
        )
        rc = 0
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.communicate()
        if rc == 0:
            shutil.rmtree(workdir, ignore_errors=True)
        else:
            print(f"fleet-smoke: artifacts kept in {workdir}")


if __name__ == "__main__":
    sys.exit(main())
