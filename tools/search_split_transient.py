"""Search for cross-shard-transient counterexamples on an R x C mesh.

The fast-flag derivation is only sound on the GLOBAL pass summary (a shard
is an open system; see stencil_packed._derive_or_replay). This searcher
finds concrete grids where the UNVOTED per-shard derivation would make the
engine exit on the wrong generation under the split-edge 2D form — pinning
material for tests/test_packed.py's split-composition transient test (the
R x C analog of test_fast_flag_cross_shard_transient).

Pure NumPy: the derivation + engine replay are simulated from oracle
states, so thousands of candidates run in seconds; hits are then validated
through the real packed-interp engine path by the test itself.

Usage: python tools/search_split_transient.py [n_seeds]
"""

from __future__ import annotations

import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from gol_tpu import oracle  # noqa: E402

T = 8  # stencil_packed.TEMPORAL_GENS
BLOCK = 16  # engine._TERMINATION_BLOCK


def shard_views(g, rows, cols):
    H, W = g.shape
    hs, ws = H // rows, W // cols
    return [
        g[r * hs : (r + 1) * hs, c * ws : (c + 1) * ws]
        for r in range(rows)
        for c in range(cols)
    ]


def run_engine_sim(g0, rows, cols, gen_limit, voted):
    """Simulate the blocked C-convention engine with fast-flag passes of T
    generations, similarity_frequency=1. Returns the reported generation
    count. ``voted``: derive from the global summary (shipped behavior) or
    per shard (the broken form the vote exists to prevent)."""
    states = [g0.astype(np.uint8)]
    # Enough states for the whole bounded run.
    for _ in range(gen_limit + BLOCK + 1):
        states.append(oracle.evolve(states[-1]))

    def flags_for_pass(p0):
        """(alive_vec, similar_vec) for the pass covering states p0..p0+T."""
        n = rows * cols
        summaries = []  # per shard: in_alive, out_alive, simT, sim1
        for s in range(n):
            sv = [shard_views(states[p0 + k], rows, cols)[s] for k in range(T + 1)]
            in_alive = int(sv[0].any())
            out_alive = int(sv[T].any())
            sim1 = int(np.array_equal(sv[1], sv[0]))
            simT = int(np.array_equal(sv[T], sv[T - 1]))
            summaries.append((in_alive, out_alive, simT, sim1))
        if voted:
            in_a = max(s[0] for s in summaries)
            out_a = max(s[1] for s in summaries)
            simT = min(s[2] for s in summaries)
            sim1 = min(s[3] for s in summaries)
            summaries = [(in_a, out_a, simT, sim1)] * n
        a_vecs, s_vecs = [], []
        for s, (in_a, out_a, simT, sim1) in enumerate(summaries):
            need = (in_a == 1 and out_a == 0) or (simT == 1 and sim1 == 0)
            if need:  # exact replay: true per-generation local flags
                sv = [shard_views(states[p0 + k], rows, cols)[s] for k in range(T + 1)]
                a = [int(sv[k + 1].any()) for k in range(T)]
                sm = [int(np.array_equal(sv[k + 1], sv[k])) for k in range(T)]
            else:
                a = [out_a] * T
                sm = [simT] * T
            a_vecs.append(a)
            s_vecs.append(sm)
        alive = [max(v[k] for v in a_vecs) for k in range(T)]
        similar = [min(v[k] for v in s_vecs) for k in range(T)]
        return alive, similar

    # Blocked C loop, freq=1 (fires every generation).
    gen, completed = 1, 0
    alive = bool(g0.any())
    similar = False
    while alive and not similar and gen <= gen_limit:
        t = min(BLOCK, gen_limit - gen + 1)
        a_all, s_all = [], []
        for j in range(t // T):
            a, s = flags_for_pass(completed + T * j)
            a_all += a
            s_all += s
        for k in range(t % T):
            st = states[completed + (t // T) * T + k + 1]
            pv = states[completed + (t // T) * T + k]
            a_all.append(int(st.any()))
            s_all.append(int(np.array_equal(st, pv)))
        # scalar replay
        for i in range(t):
            sim_i = bool(s_all[i])
            alive = bool(a_all[i])
            if not sim_i:
                gen += 1
            similar = sim_i
            if not (alive and not sim_i and gen <= gen_limit):
                break
        completed += i + 1
    return gen - 1


def main():
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 3000
    rows, cols = 2, 2
    H, W = 64, 256  # 32x128 shards: nwords=4 >= 2 -> split-edge form
    gen_limit = 30
    rng = np.random.default_rng(0)
    hits = []
    for seed in range(n_seeds):
        r = np.random.default_rng(seed)
        g = np.zeros((H, W), np.uint8)
        # Sparse cells clustered near the column seam (W//2) and a row seam
        # (H//2): transients must CROSS shard boundaries to make a local
        # summary lie.
        n_cells = int(r.integers(6, 14))
        rr = r.integers(H // 2 - 4, H // 2 + 4, size=n_cells)
        cc = r.integers(W // 2 - 5, W // 2 + 5, size=n_cells)
        g[rr, cc] = 1
        want = run_engine_sim(g, rows, cols, gen_limit, voted=True)
        broken = run_engine_sim(g, rows, cols, gen_limit, voted=False)
        if want != broken:
            # Sanity: voted must equal the true oracle count.
            true = oracle.run(g, __import__("gol_tpu.config", fromlist=["GameConfig"]).GameConfig(gen_limit=gen_limit, similarity_frequency=1)).generations
            hits.append((seed, sorted(set(map(int, rr))), sorted(set(map(int, cc))), want, broken, true))
            print(f"seed {seed}: voted={want} broken={broken} oracle={true} "
                  f"rows={sorted(set(map(int,rr)))} cols={sorted(set(map(int,cc)))}")
            if len(hits) >= 4:
                break
    if not hits:
        print("no counterexample found", file=sys.stderr)
        return 1
    for seed, rr, cc, want, broken, true in hits:
        r = np.random.default_rng(seed)
        n_cells = int(r.integers(6, 14))
        rrr = r.integers(H // 2 - 4, H // 2 + 4, size=n_cells).tolist()
        ccc = r.integers(W // 2 - 5, W // 2 + 5, size=n_cells).tolist()
        print(f"  pin: rows={rrr} cols={ccc}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
