"""Resident mega-batch crash/restart smoke: SIGKILL mid-ring, replay, verify.

The `make megabatch-smoke` harness (mirroring `make pipeline-smoke`),
exercising the resident ring lanes against real OS processes:

1. **Kill half** — a ``gol serve --resident-ring 4 --pipeline-depth 8``
   session takes jobs across two padding buckets (an exact-fit packed
   bucket and a masked one) and is SIGKILLed while ring drains are in
   flight — no Python unwinding, like a power cut. A restarted server on
   the same journal replays exactly the unfinished jobs; after a drain,
   every accepted job is DONE exactly once (one `done` record per id in
   the journal) and every result is byte-identical to a solo `gol run` of
   the same board.

2. **A/B half** — the same job set served by a classic ``--pipeline-depth
   1`` server must return byte-identical grids, generation counts, and
   exit reasons (the resident lane is a pure performance change).

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/megabatch_smoke.py [--jobs 16] [--gen-limit 300]
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("GOL_FAULTS", None)
    return env


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _start(port, journal_dir, *extra):
    return subprocess.Popen(
        [sys.executable, "-m", "gol_tpu", "serve", "--port", str(port),
         "--journal-dir", journal_dir, "--flush-age", "0.001",
         "--max-batch", "4", *extra],
        env=_env(), cwd=ROOT, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


def _wait_up(proc, base, timeout=180):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"server died rc={proc.returncode}:\n{proc.stdout.read()}"
            )
        try:
            code, _ = _http("GET", base + "/healthz", timeout=5)
            if code == 200:
                return
        except (urllib.error.URLError, OSError):
            time.sleep(0.05)
    raise RuntimeError("server did not come up")


def _stop(proc):
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()


def _collect(base, ids, timeout):
    results = {}
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline and len(results) < len(ids):
        for jid in ids:
            if jid in results:
                continue
            code, out = _http("GET", f"{base}/result/{jid}")
            if code == 200:
                results[jid] = out
        time.sleep(0.05)
    return results


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--jobs", type=int, default=16)
    ap.add_argument("--gen-limit", type=int, default=300)
    ap.add_argument("--kill-after", type=float, default=0.5,
                    help="seconds after the last submit to SIGKILL")
    args = ap.parse_args()

    from gol_tpu.io import text_grid  # noqa: E402 - after sys.path insert

    workdir = tempfile.mkdtemp(prefix="gol-megabatch-smoke-")
    journal_dir = os.path.join(workdir, "journal")
    resident = ["--resident-ring", "4", "--pipeline-depth", "8"]
    boards = []
    for i in range(args.jobs):
        side = 64 if i % 2 == 0 else 60  # packed + masked buckets
        boards.append(text_grid.generate(side, side, seed=8000 + i))
    payloads = [
        {"width": b.shape[1], "height": b.shape[0],
         "gen_limit": args.gen_limit,
         "cells": text_grid.encode(b).decode("ascii")}
        for b in boards
    ]

    ok = True
    # -- 1. SIGKILL mid-ring, replay, drain --------------------------------
    port = _free_port()
    base = f"http://127.0.0.1:{port}"
    proc = _start(port, journal_dir, *resident)
    ids = []
    try:
        _wait_up(proc, base)
        for payload in payloads:
            code, out = _http("POST", base + "/jobs", payload)
            if code != 202:
                print(f"megabatch-smoke: submit rejected {code}: {out}")
                return 1
            ids.append(out["id"])
        time.sleep(args.kill_after)
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()

    with open(os.path.join(journal_dir, "journal.jsonl"), "rb") as f:
        done_before = sum(
            1 for line in f.read().splitlines()
            if line and json.loads(line).get("event") == "done"
        )
    print(f"megabatch-smoke: SIGKILL'd resident server; journal shows "
          f"{done_before}/{args.jobs} done pre-kill")

    port2 = _free_port()
    base2 = f"http://127.0.0.1:{port2}"
    proc2 = _start(port2, journal_dir, *resident)
    try:
        _wait_up(proc2, base2)
        results = _collect(base2, ids, timeout=300)
    finally:
        _stop(proc2)
    if len(results) != len(ids):
        print(f"megabatch-smoke: {len(ids) - len(results)} job(s) never "
              f"finished after replay")
        return 1

    with open(os.path.join(journal_dir, "journal.jsonl"), "rb") as f:
        events = [json.loads(line) for line in f.read().splitlines() if line]
    for jid in ids:
        dones = [e for e in events
                 if e.get("event") == "done" and e.get("id") == jid]
        if len(dones) != 1:
            print(f"megabatch-smoke: job {jid} has {len(dones)} done "
                  f"records (want exactly 1)")
            ok = False

    # -- 2. A/B: classic depth-1 serve must match byte for byte ------------
    port3 = _free_port()
    base3 = f"http://127.0.0.1:{port3}"
    proc3 = _start(port3, os.path.join(workdir, "journal-classic"))
    try:
        _wait_up(proc3, base3)
        classic_ids = []
        for payload in payloads:
            code, out = _http("POST", base3 + "/jobs", payload)
            if code != 202:
                print(f"megabatch-smoke: classic submit rejected {code}")
                return 1
            classic_ids.append(out["id"])
        classic = _collect(base3, classic_ids, timeout=300)
    finally:
        _stop(proc3)
    if len(classic) != len(classic_ids):
        print("megabatch-smoke: classic lane failed to finish")
        return 1
    for jid, cid in zip(ids, classic_ids):
        a, b = results[jid], classic[cid]
        if (a["grid"] != b["grid"] or a["generations"] != b["generations"]
                or a["exit_reason"] != b["exit_reason"]):
            print(f"megabatch-smoke: resident result for {jid} diverges "
                  f"from the classic lane")
            ok = False

    shutil.rmtree(workdir, ignore_errors=True)
    if ok:
        print(f"megabatch-smoke: OK — {args.jobs} jobs exactly-once across "
              f"SIGKILL mid-ring + replay; resident byte-identical to "
              f"classic depth-1")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
