"""Elastic-fleet smoke: spike -> scale-up -> kill -> replay -> scale-down.

The `make autoscale-smoke` harness, exercising the autoscaler acceptance
end-to-end against real OS processes (the real `gol fleet` CLI with
`--autoscale`, real `gol serve` workers, real SIGKILL):

1. boot ``gol fleet --workers 1 --autoscale --max-workers 3`` with
   aggressive bench knobs (fast health ticks, short cooldown, low
   saturation threshold) on a fresh ``--fleet-dir``;
2. apply a STEP LOAD: a feeder keeps ~160 jobs outstanding across eight
   equal-work 160^2 buckets (every worker is pinned to its own 4-core
   slice, so one worker is genuinely saturable) — queue saturation must
   trip the autoscaler, and ``GET /fleet`` must show the fleet growing;
3. SIGKILL one SCALED worker mid-load: the health loop must respawn it
   on its partition and replay; the load keeps flowing meanwhile
   (spillover), and the autoscaler must not fight the supervisor;
4. stop the load and wait: every accepted job reports DONE through the
   router, results spot-check byte-identical to the NumPy oracle;
5. the idle fleet must retire back down to the ``--min-workers 1``
   floor (drain -> retire, never losing a job);
6. SIGTERM the fleet (cascaded drain, rc 0), then audit ACROSS ALL
   journal partitions — including retired workers' partitions, which
   stay on disk — that every accepted id has EXACTLY one done record
   fleet-wide.

Exit code 0 on success, 1 with a diagnostic on any violation:

    python tools/autoscale_smoke.py [--jobs 600] [--gen-limit 3000]
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from gol_tpu import oracle  # noqa: E402
from gol_tpu.config import GameConfig  # noqa: E402
from gol_tpu.io import text_grid  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# 8 equal-work buckets on one 160^2 canvas (distinct similarity
# frequencies are baked program constants, so each is its own padding
# bucket): enough buckets that rendezvous placement actually hands the
# scaled-up workers load, the same trick as bench.py's fleet suites.
SIDE = 160
FREQS = (2, 3, 4, 5, 6, 7, 8, 9)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _start_fleet(port: int, fleet_dir: str):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "gol_tpu", "fleet",
            "--port", str(port),
            "--workers", "1",
            "--fleet-dir", fleet_dir,
            "--flush-age", "0.05",
            "--health-interval", "0.4",
            "--max-queue-depth", "256",
            "--max-batch", "8",
            # Pin every worker (incl. autoscaled spawns) to its own
            # 4-core slice: the fixed per-worker budget that makes one
            # worker saturable on a many-core host AND makes scale-up a
            # real capacity increase.
            "--cores-per-worker", "4",
            "--autoscale",
            "--min-workers", "1",
            "--max-workers", "3",
            "--scale-up-saturation", "0.2",
            "--scale-up-sustain", "2",
            "--scale-down-occupancy", "0.02",
            "--scale-down-sustain", "8",
            "--scale-cooldown", "2",
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    deadline = time.perf_counter() + 300
    base = f"http://127.0.0.1:{port}"
    while time.perf_counter() < deadline:
        if proc.poll() is not None:
            out, _ = proc.communicate()
            raise RuntimeError(
                f"fleet died on boot rc={proc.returncode}:\n{out[-4000:]}"
            )
        try:
            status, payload = _http("GET", f"{base}/healthz", timeout=2)
            if status == 200 and payload.get("fleet", {}).get("workers", 0) >= 1:
                return proc
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.2)
    proc.kill()
    raise RuntimeError("fleet did not become healthy within 300s")


def _workers(base: str) -> list:
    status, payload = _http("GET", f"{base}/fleet")
    if status != 200:
        raise RuntimeError(f"GET /fleet -> {status}: {payload}")
    return payload["workers"]


def _count_done(fleet_dir: str) -> dict:
    # compaction.iter_records (snapshot + sealed segments + live file):
    # the audit survives journal rotation/compaction on busy partitions.
    from gol_tpu.serve import compaction

    done: dict = {}
    for name in sorted(os.listdir(fleet_dir)):
        part = os.path.join(fleet_dir, name)
        if not os.path.isfile(os.path.join(part, "journal.jsonl")):
            continue
        for rec in compaction.iter_records(part):
            if rec.get("event") == "done":
                done.setdefault(rec["id"], []).append((name, rec))
    return done


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--jobs", type=int, default=600,
                        help="total jobs the step load submits")
    parser.add_argument("--gen-limit", type=int, default=3000)
    parser.add_argument("--outstanding", type=int, default=160,
                        help="jobs the feeder keeps in flight (the step)")
    args = parser.parse_args(argv)

    workdir = tempfile.mkdtemp(prefix="gol-autoscale-smoke-")
    fleet_dir = os.path.join(workdir, "fleet")
    port = _free_port()
    base = f"http://127.0.0.1:{port}"

    rc = 1
    proc = None
    accepted: dict = {}  # id -> (board, similarity frequency)
    acc_lock = threading.Lock()
    stop_feed = threading.Event()
    feed_error = []

    def feeder():
        i = 0
        try:
            while not stop_feed.is_set() and i < args.jobs:
                with acc_lock:
                    n_acc = len(accepted)
                status, snap = _http("GET", f"{base}/metrics?format=json",
                                     timeout=10)
                done = int((snap.get("counters") or {})
                           .get("jobs_completed_total", 0)) \
                    if status == 200 else 0
                if n_acc - done >= args.outstanding:
                    time.sleep(0.1)
                    continue
                freq = FREQS[i % len(FREQS)]
                board = text_grid.generate(SIDE, SIDE, seed=7000 + i)
                status, payload = _http("POST", f"{base}/jobs", {
                    "width": SIDE, "height": SIDE,
                    "cells": text_grid.encode(board).decode("ascii"),
                    "gen_limit": args.gen_limit,
                    "similarity_frequency": freq,
                })
                if status == 429:
                    time.sleep(0.2)  # shed burst mid-scale: back off, retry
                    continue
                if status != 202:
                    raise RuntimeError(
                        f"submit {i} rejected HTTP {status}: {payload}")
                with acc_lock:
                    accepted[payload["id"]] = (board, freq)
                i += 1
        except Exception as err:  # noqa: BLE001 - surfaced by the main thread
            feed_error.append(err)

    try:
        proc = _start_fleet(port, fleet_dir)
        print(f"autoscale-smoke: 1-worker autoscaled fleet up on {base}")

        feed = threading.Thread(target=feeder, daemon=True)
        t_spike = time.perf_counter()
        feed.start()

        # 2. the step load must grow the fleet. Wait for a scaled worker
        # that is READY (has a URL — /fleet lists workers from launch
        # time, before their boot banner): killing one mid-boot hits the
        # spawn-rollback lane (the autoscaler re-spawns a FRESH worker
        # after cooldown) instead of the supervised-respawn lane this
        # smoke exists to prove.
        deadline = time.perf_counter() + 420
        victim = None
        while victim is None:
            if feed_error:
                raise feed_error[0]
            workers = _workers(base)
            victim = next(
                (w for w in workers
                 if w["id"] != "w0" and w.get("url") and w.get("pid")
                 and w.get("healthy")),
                None,
            )
            if victim is None:
                if time.perf_counter() > deadline:
                    raise RuntimeError(
                        f"fleet never scaled up under the step load: "
                        f"{workers}")
                time.sleep(0.3)
        print(f"autoscale-smoke: scale-up observed "
              f"{time.perf_counter() - t_spike:.1f}s after the spike "
              f"({len(workers)} workers)")

        # 3. SIGKILL that SCALED worker (not the original w0) mid-load.
        print(f"autoscale-smoke: SIGKILL scaled worker {victim['id']} "
              f"(pid {victim['pid']}) mid-load")
        os.kill(victim["pid"], signal.SIGKILL)

        # The supervisor must respawn it on the SAME partition.
        deadline = time.perf_counter() + 300
        while True:
            if feed_error:
                raise feed_error[0]
            respawned = next((w for w in _workers(base)
                              if w["id"] == victim["id"]
                              and w.get("restarts", 0) >= 1
                              and w.get("healthy")), None)
            if respawned is not None:
                break
            if time.perf_counter() > deadline:
                raise RuntimeError(f"worker {victim['id']} never respawned")
            time.sleep(0.3)
        print(f"autoscale-smoke: {victim['id']} respawned on its partition")

        # 4. stop the load; every accepted job must reach DONE.
        feed.join(timeout=600)
        stop_feed.set()
        if feed_error:
            raise feed_error[0]
        with acc_lock:
            pending = set(accepted)
        # Every 40th job is the oracle sample; its result is fetched the
        # moment it completes — fetching after the load ends would race
        # the scale-down, whose retired workers take their (already
        # audited-by-journal) results with them.
        sample = set(list(accepted)[::40])
        fetched: dict = {}
        print(f"autoscale-smoke: load stopped ({len(pending)} accepted); "
              "waiting for DONE fleet-wide")
        deadline = time.perf_counter() + 600
        while pending and time.perf_counter() < deadline:
            for job_id in list(pending):
                try:
                    status, payload = _http("GET", f"{base}/jobs/{job_id}",
                                            timeout=10)
                except (urllib.error.URLError, OSError):
                    break
                if status >= 500:
                    continue  # respawn/retire window: keep polling
                if status != 200:
                    print(f"autoscale-smoke: job {job_id} LOST "
                          f"(HTTP {status}: {payload})")
                    return 1
                state = payload["state"]
                if state == "done":
                    if job_id in sample:
                        status, result = _http(
                            "GET", f"{base}/result/{job_id}", timeout=10)
                        if status >= 500:
                            continue  # transient: re-fetch next sweep
                        if status != 200:
                            print(f"autoscale-smoke: result {job_id} "
                                  f"HTTP {status}")
                            return 1
                        fetched[job_id] = result
                    pending.discard(job_id)
                elif state in ("failed", "cancelled"):
                    print(f"autoscale-smoke: job {job_id} ended {state}")
                    return 1
            if pending:
                time.sleep(0.2)
        if pending:
            print(f"autoscale-smoke: {len(pending)} job(s) never completed")
            return 1

        # Oracle-gate the sampled results (offline; no HTTP to race).
        for job_id, result in fetched.items():
            board, freq = accepted[job_id]
            want = oracle.run(board, GameConfig(
                gen_limit=args.gen_limit, similarity_frequency=freq))
            got = text_grid.decode(result["grid"].encode("ascii"),
                                   result["width"], result["height"])
            if (not np.array_equal(np.asarray(got), want.grid)
                    or result["generations"] != want.generations):
                print(f"autoscale-smoke: result {job_id} diverges from "
                      "the oracle")
                return 1
        print(f"autoscale-smoke: all jobs DONE, {len(fetched)} results "
              "oracle-identical through the kill and the scale events")

        # 5. the idle fleet must retire to the floor.
        deadline = time.perf_counter() + 420
        while True:
            workers = _workers(base)
            if len(workers) == 1:
                break
            if time.perf_counter() > deadline:
                raise RuntimeError(
                    f"fleet never retired to the floor: {workers}")
            time.sleep(0.5)
        print("autoscale-smoke: scale-down retired the fleet to the "
              "1-worker floor")

        # 6. cascaded SIGTERM exit + fleet-wide exactly-once audit.
        proc.send_signal(signal.SIGTERM)
        try:
            out, _ = proc.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            print("autoscale-smoke: fleet ignored SIGTERM")
            proc.kill()
            return 1
        if proc.returncode != 0:
            print(f"autoscale-smoke: fleet exited rc={proc.returncode}:\n"
                  f"{out[-3000:]}")
            return 1
        proc = None

        done = _count_done(fleet_dir)
        lost = set(accepted) - set(done)
        extra = set(done) - set(accepted)
        dup = {k: [p for p, _ in v] for k, v in done.items() if len(v) != 1}
        if lost or extra or dup:
            print(f"autoscale-smoke: lost={lost} unknown={extra} "
                  f"duplicated={dup}")
            return 1
        partitions = {p for v in done.values() for p, _ in v}
        history = os.path.join(fleet_dir, "autoscaler-history")
        decisions = os.path.isdir(history) and bool(os.listdir(history))
        if not decisions:
            print("autoscale-smoke: no autoscaler decision ring was written")
            return 1
        print(
            f"autoscale-smoke: PASS — {len(accepted)} jobs exactly-once "
            f"across {len(partitions)} partitions (incl. retired ones), "
            "scale-up under load, SIGKILL replayed, scale-down to floor, "
            "decision ring present"
        )
        rc = 0
        return 0
    finally:
        stop_feed.set()
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.communicate()
        if rc == 0:
            shutil.rmtree(workdir, ignore_errors=True)
        else:
            print(f"autoscale-smoke: artifacts kept in {workdir}")


if __name__ == "__main__":
    sys.exit(main())
