"""Round-5 TPU measurement battery (VERDICT r4 items 1, 2, 7).

Protocol upgrades over r3 (documented in benchmarks/README.md):

- RATIOS are computed within one process from interleaved chained runs
  (round-robin across the compared paths), as in r3 — but the published
  number is now the MEDIAN across >= 5 fresh-process sessions, with the
  full per-session series recorded. The attach tunnel's chip throughput
  drifts between processes (r3 measured ±35%); medians of interleaved
  ratios are the statistic that survives it.
- Chains are longer (marginal over >= 200 temporal passes) so the
  two-length subtraction amortizes the ~90 ms dispatch floor to < 2%.
- Best-effort DEVICE time per pass from a jax.profiler trace parsed with
  xprof (immune to tunnel weather between dispatch and completion);
  recorded alongside wall-clock marginals when the parse succeeds.

Subcommands:

    python tools/measure_r5.py session <size>   # one interleaved session, JSON to stdout
    python tools/measure_r5.py compare <size> [sessions=5]
    python tools/measure_r5.py podshard [sessions=5]   # BASELINE config-5 shard: 16x1 vs 4x4
    python tools/measure_r5.py all

compare writes benchmarks/compare_<size>_r5.json; podshard writes
benchmarks/configs_r5.json (the 16x1-vs-4x4 reconciliation, item 5).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "benchmarks")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _host_words(h: int, w: int, seed: int = 42) -> np.ndarray:
    rng = np.random.default_rng(seed)
    grid = rng.integers(0, 2, size=(h, w), dtype=np.uint8)
    return np.packbits(grid, axis=1, bitorder="little").view(np.uint32)


def _force(x) -> None:
    # block_until_ready is unreliable over the attach tunnel; a scalar
    # readback is the only dependable completion barrier.
    int(np.asarray(x[0, 0]))


def _device_time_per_pass(fn, words, n: int):
    """Best-effort: total TPU device time for one n-pass chain, via xprof.

    Returns ms per pass or None if the trace/parse path is unavailable.
    """
    import glob
    import tempfile

    import jax

    try:
        from xprof.convert import raw_to_tool_data
    except Exception:
        return None
    try:
        with tempfile.TemporaryDirectory() as td:
            with jax.profiler.trace(td):
                _force(fn(words, n))
            planes = glob.glob(os.path.join(td, "**", "*.xplane.pb"),
                               recursive=True)
            if not planes:
                return None
            data, _ = raw_to_tool_data.xspace_to_tool_data(
                planes, "op_profile", {}
            )
            if isinstance(data, bytes):
                data = data.decode("utf-8", "replace")
            # op_profile's byProgram rawTime is total DEVICE picoseconds in
            # the traced window — the chain dominates it (dispatch and the
            # tunnel never appear in device time).
            raw_ps = json.loads(data)["byProgram"]["metrics"]["rawTime"]
            return raw_ps / 1e9 / n
    except Exception as e:  # noqa: BLE001 - best effort, never fail the session
        log("device-time parse failed:", type(e).__name__, str(e)[:120])
        return None


def session(size: int, reps: int = 3, trace: bool = True) -> dict:
    """One process's interleaved A/B/C: single-chip temporal vs rows-only
    mesh form vs split-edge 2D form, marginal over two chain lengths."""
    import jax
    import jax.numpy as jnp

    from gol_tpu.ops import stencil_packed as sp
    from gol_tpu.parallel.mesh import PROXY_2D, SINGLE_DEVICE

    assert jax.default_backend() == "tpu", jax.default_backend()
    T = sp.TEMPORAL_GENS
    words = jnp.asarray(_host_words(size, size))

    def chain(step):
        def fn(w, n):
            return jax.lax.fori_loop(0, n, lambda i, x: step(x), w)
        return jax.jit(fn, static_argnums=1)

    paths = {
        # 'single' is the r4 denominator (exact per-generation flags), kept
        # for round-over-round comparability; 'single_fast' is what the
        # engine actually runs on one chip since the fast-flag passes
        # (packed_step_multi -> _step_t_fast) — the honest denominator for
        # "what does a pod chip pay vs a single chip".
        "single": chain(lambda w: sp._step_t(w)[0]),
        "single_fast": chain(lambda w: sp._step_t_fast(w)[0]),
        "rows": chain(lambda w: sp._distributed_step_multi(w, SINGLE_DEVICE)[0]),
        "split2d": chain(lambda w: sp._distributed_step_multi(w, PROXY_2D)[0]),
    }
    # Chain lengths: >= 200 passes of margin, scaled down for the larger grid.
    n1, n2 = (50, 250) if size <= 16384 else (25, 100)

    # Compile + warm every path before any timing.
    for name, fn in paths.items():
        t0 = time.time()
        _force(fn(words, 2))
        log(f"  warm {name}: {time.time() - t0:.0f}s")

    def timed(fn, n):
        t0 = time.perf_counter()
        _force(fn(words, n))
        return time.perf_counter() - t0

    # Discard round: the first full-length timed pass after compile absorbs
    # one-time upload/init effects (observed as negative marginals otherwise).
    for fn in paths.values():
        timed(fn, n1)

    rates = {k: [] for k in paths}
    for rep in range(reps):
        # Interleave across paths at both lengths within each rep.
        t1 = {k: timed(fn, n1) for k, fn in paths.items()}
        t2 = {k: timed(fn, n2) for k, fn in paths.items()}
        for k in paths:
            per_pass = (t2[k] - t1[k]) / (n2 - n1)
            rates[k].append(size * size * T / per_pass)
        log(f"  rep {rep}: " + ", ".join(
            f"{k}={rates[k][-1] / 1e12:.2f}T" for k in paths))

    med = {k: sorted(v)[len(v) // 2] for k, v in rates.items()}
    out = {
        "size": size,
        "reps": reps,
        "chain_lengths": [n1, n2],
        "cells_per_s": {k: [round(r, 0) for r in v] for k, v in rates.items()},
        "ratio_rows": round(med["rows"] / med["single"], 4),
        "ratio_2d": round(med["split2d"] / med["single"], 4),
        "ratio_rows_vs_fast": round(med["rows"] / med["single_fast"], 4),
        "ratio_2d_vs_fast": round(med["split2d"] / med["single_fast"], 4),
        "single_median_cells_per_s": round(med["single"], 0),
        "single_fast_median_cells_per_s": round(med["single_fast"], 0),
    }
    if trace:
        dt = {k: _device_time_per_pass(fn, words, n1) for k, fn in paths.items()}
        if all(v is not None for v in dt.values()):
            out["device_ms_per_pass"] = {k: round(v, 3) for k, v in dt.items()}
            out["device_ratio_rows"] = round(dt["single"] / dt["rows"], 4)
            out["device_ratio_2d"] = round(dt["single"] / dt["split2d"], 4)
            out["device_ratio_rows_vs_fast"] = round(
                dt["single_fast"] / dt["rows"], 4)
            out["device_ratio_2d_vs_fast"] = round(
                dt["single_fast"] / dt["split2d"], 4)
        else:
            out["device_ms_per_pass"] = None
    return out


def compare(size: int, sessions: int = 5) -> None:
    """Run `sessions` fresh-process sessions; publish medians + full series."""
    results = []
    for i in range(sessions):
        log(f"session {i + 1}/{sessions} (size {size})")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "session", str(size)],
            capture_output=True, text=True, cwd=REPO, timeout=3600,
        )
        if proc.returncode != 0:
            log(f"  session failed: {proc.stderr[-800:]}")
            continue
        line = proc.stdout.strip().splitlines()[-1]
        results.append(json.loads(line))
        log(f"  ratios: rows={results[-1]['ratio_rows']} "
            f"2d={results[-1]['ratio_2d']}")
    if not results:
        raise SystemExit("no session succeeded")
    ratios_rows = sorted(r["ratio_rows"] for r in results)
    ratios_2d = sorted(r["ratio_2d"] for r in results)
    payload = {
        "protocol": "interleaved chained marginals; median across fresh-process "
                    "sessions (see benchmarks/README.md, r4 protocol)",
        "size": size,
        "sessions": results,
        "runs_rows_ratio": ratios_rows,
        "runs_2d_ratio": ratios_2d,
        "rows_ratio_median": ratios_rows[len(ratios_rows) // 2],
        "2d_ratio_median": ratios_2d[len(ratios_2d) // 2],
    }
    path = os.path.join(OUT, f"compare_{size}_r5.json")
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    log("wrote", path)


def podshard_session() -> dict:
    """BASELINE config 5's per-chip shard both ways, one interleaved session:
    16x1 rows-only -> a (4096, 65536) shard; 4x4 2D -> a (16384, 16384)
    shard. Plus the single-chip temporal rate on the SAME (4096, 65536)
    array as the shared denominator."""
    import jax
    import jax.numpy as jnp

    from gol_tpu.ops import stencil_packed as sp
    from gol_tpu.parallel.mesh import PROXY_2D, SINGLE_DEVICE

    assert jax.default_backend() == "tpu"
    T = sp.TEMPORAL_GENS
    shard_16x1 = jnp.asarray(_host_words(4096, 65536))
    shard_4x4 = jnp.asarray(_host_words(16384, 16384, seed=43))

    def chain(step):
        def fn(w, n):
            return jax.lax.fori_loop(0, n, lambda i, x: step(x), w)
        return jax.jit(fn, static_argnums=1)

    runs = {
        "single_ref": (chain(lambda w: sp._step_t(w)[0]), shard_16x1),
        "rows_16x1": (
            chain(lambda w: sp._distributed_step_multi(w, SINGLE_DEVICE)[0]),
            shard_16x1,
        ),
        "split2d_4x4": (
            chain(lambda w: sp._distributed_step_multi(w, PROXY_2D)[0]),
            shard_4x4,
        ),
    }
    n1, n2 = 25, 100
    for name, (fn, w) in runs.items():
        t0 = time.time()
        _force(fn(w, 2))
        log(f"  warm {name}: {time.time() - t0:.0f}s")
    for fn, w in runs.values():  # discard round (see session())
        _force(fn(w, n1))
    rates = {k: [] for k in runs}
    for rep in range(3):
        t1 = {k: None for k in runs}
        t2 = {k: None for k in runs}
        for k, (fn, w) in runs.items():
            t0 = time.perf_counter(); _force(fn(w, n1)); t1[k] = time.perf_counter() - t0
        for k, (fn, w) in runs.items():
            t0 = time.perf_counter(); _force(fn(w, n2)); t2[k] = time.perf_counter() - t0
        for k in runs:
            per_pass = (t2[k] - t1[k]) / (n2 - n1)
            cells = 4096 * 65536  # both shards are the same cell count
            rates[k].append(cells * T / per_pass)
        log(f"  rep {rep}: " + ", ".join(f"{k}={rates[k][-1]/1e12:.2f}T" for k in runs))
    med = {k: sorted(v)[len(v) // 2] for k, v in rates.items()}
    return {
        "cells_per_s": {k: [round(x) for x in v] for k, v in rates.items()},
        "ratio_rows_16x1": round(med["rows_16x1"] / med["single_ref"], 4),
        "ratio_split2d_4x4": round(med["split2d_4x4"] / med["single_ref"], 4),
        "single_ref_cells_per_s": round(med["single_ref"]),
    }


def podshard(sessions: int = 5) -> None:
    results = []
    for i in range(sessions):
        log(f"podshard session {i + 1}/{sessions}")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "podshard-session"],
            capture_output=True, text=True, cwd=REPO, timeout=3600,
        )
        if proc.returncode != 0:
            log(f"  session failed: {proc.stderr[-800:]}")
            continue
        results.append(json.loads(proc.stdout.strip().splitlines()[-1]))
        log(f"  ratios: 16x1={results[-1]['ratio_rows_16x1']} "
            f"4x4={results[-1]['ratio_split2d_4x4']}")
    if not results:
        raise SystemExit("no session succeeded")
    r16 = sorted(r["ratio_rows_16x1"] for r in results)
    r44 = sorted(r["ratio_split2d_4x4"] for r in results)
    payload = {
        "what": "BASELINE config 5 (65536^2 on 16 chips) per-chip shard, both "
                "meshes, one chip with local wraps standing in for ICI "
                "ppermutes; ratios vs the single-chip temporal rate on the "
                "same cell count",
        "sessions": results,
        "ratio_16x1_runs": r16,
        "ratio_4x4_runs": r44,
        "ratio_16x1_median": r16[len(r16) // 2],
        "ratio_4x4_median": r44[len(r44) // 2],
    }
    path = os.path.join(OUT, "configs_r5.json")
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    log("wrote", path)


def main() -> None:
    cmd = sys.argv[1] if len(sys.argv) > 1 else "all"
    if cmd == "session":
        print(json.dumps(session(int(sys.argv[2]))))
    elif cmd == "podshard-session":
        print(json.dumps(podshard_session()))
    elif cmd == "compare":
        compare(int(sys.argv[2]), int(sys.argv[3]) if len(sys.argv) > 3 else 5)
    elif cmd == "podshard":
        podshard(int(sys.argv[2]) if len(sys.argv) > 2 else 5)
    elif cmd == "all":
        compare(16384)
        compare(32768)
        podshard()
    else:
        raise SystemExit(f"unknown subcommand {cmd}")


if __name__ == "__main__":
    main()
