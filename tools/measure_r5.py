"""Thin shim: the r5 measurement battery lives in tools/measure.py (--rev 5).

Kept so documented commands (`python tools/measure_r5.py compare 16384` etc.)
keep working; `--rev 5` is also measure.py's default, so the plain
`python tools/measure.py <step>` form is equivalent. The argument mapping
lives in measure.py's ``_SHIM_ARGS`` table.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from measure import shim_main  # noqa: E402

if __name__ == "__main__":
    sys.exit(shim_main(__file__))
