"""Write-pipeline loopback: ``write_packed`` with D2H removed (VERDICT r3 #3).

The config-5 write phase on the attach tunnel is bounded by the tunnel's raw
D2H floor (benchmarks/d2h_probe_r3.json), which leaves open whether the
fetch -> codec-unpack -> memmap chain itself would saturate a real
PCIe-attached chip. This measures exactly that chain with the transfer taken
out of the equation: the word state lives on the CPU backend (fetch is a
memcpy), the file lands on tmpfs (no disk writeback in the loop), so the
remaining cost IS the pipeline — chunking, prefetch bookkeeping, the SWAR
codec, and the memmap stores.

    JAX_PLATFORMS=cpu python tools/write_loopback_r4.py [size=32768]

Writes benchmarks/write_loopback_r4.json: text-emit GB/s per run plus the
bare codec unpack rate for comparison (how much the pipeline machinery
costs over the codec itself). The read direction (pack) is probed the same
way for completeness.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

from gol_tpu import native
from gol_tpu.io import packed_io
from gol_tpu.io.text_grid import row_stride

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "benchmarks", "write_loopback_r4.json")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    size = int(sys.argv[1]) if len(sys.argv) > 1 else 32768
    repeats = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    assert jax.default_backend() == "cpu", jax.default_backend()
    rng = np.random.default_rng(7)
    host_words = rng.integers(
        0, np.iinfo(np.uint32).max, size=(size, size // 32),
        dtype=np.uint32, endpoint=True,
    )
    words = jax.numpy.asarray(host_words)
    text_bytes = size * row_stride(size)
    tmpdir = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()
    log(f"loopback {size}x{size}: {text_bytes / 1e9:.2f} GB of text -> {tmpdir}")

    runs = []
    path = os.path.join(tmpdir, "gol_write_loopback.out")
    try:
        for i in range(repeats):
            t0 = time.perf_counter()
            packed_io.write_packed(path, words, size)
            dt = time.perf_counter() - t0
            runs.append(text_bytes / dt / 1e9)
            log(f"  write run {i}: {dt * 1000:.0f} ms = {runs[-1]:.2f} GB/s text")

        # Bare codec rate (single thread, no pipeline): one representative
        # 64MB-word block unpacked straight into a tmpfs memmap window.
        rows = max(1, (64 << 20) // (size // 32 * 4))
        block = np.ascontiguousarray(host_words[:rows])
        window = np.memmap(path, dtype=np.uint8, mode="r+",
                           shape=(rows, row_stride(size)))
        codec_runs = []
        for i in range(repeats):
            t0 = time.perf_counter()
            native.unpack_text(block, window, size, True)
            dt = time.perf_counter() - t0
            codec_runs.append(rows * row_stride(size) / dt / 1e9)
        del window
        log(f"  bare codec unpack: {max(codec_runs):.2f} GB/s/thread")

        # Read direction for completeness: text file -> packed device array.
        read_runs = []
        for i in range(repeats):
            t0 = time.perf_counter()
            got = packed_io.read_packed(path, size, size)
            got.block_until_ready()
            dt = time.perf_counter() - t0
            read_runs.append(text_bytes / dt / 1e9)
            del got
            log(f"  read run {i}: {dt * 1000:.0f} ms = {read_runs[-1]:.2f} GB/s text")
    finally:
        if os.path.exists(path):
            os.unlink(path)

    payload = {
        "purpose": "write_packed pipeline rate with D2H removed (CPU backend, tmpfs)",
        "size": size,
        "text_gb": text_bytes / 1e9,
        "tmpdir": tmpdir,
        "cpus": os.cpu_count(),
        "write_gb_per_s": [round(r, 3) for r in runs],
        "write_median_gb_per_s": round(sorted(runs)[len(runs) // 2], 3),
        "codec_unpack_gb_per_s_single_thread": round(max(codec_runs), 3),
        "read_gb_per_s": [round(r, 3) for r in read_runs],
        "read_median_gb_per_s": round(sorted(read_runs)[len(read_runs) // 2], 3),
    }
    with open(OUT, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    log("wrote", OUT)
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
