"""Thin shim: the termination-block A/B lives in tools/measure.py (`block`).

Kept so the documented command (`python tools/measure_block_r5.py [size]
[gens] [blocks...]`) keeps working; the argument mapping lives in
measure.py's ``_SHIM_ARGS`` table. The A/B builds each block size through
the engine's per-runner plan parameter (gol_tpu/tune/space.EnginePlan).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from measure import shim_main  # noqa: E402

if __name__ == "__main__":
    sys.exit(shim_main(__file__))
