"""Termination-block A/B at the headline size (VERDICT r4 item 7).

The 65536^2 wall rate (2.96e12, BENCH_r04) trails the 16384^2 post-fast-flag
device rate (~3.46e12). One candidate cost: the blocked while_loop syncs
flags every _TERMINATION_BLOCK=16 generations (2 fused passes per block) —
each outer iteration ends in a vector vote + 16-step scalar replay between
the flag production and the loop cond. With fast flags the per-pass flag
cost is ~gone, so a larger block may amortize the remaining per-block cost.

A/B protocol per the r4 measurement notes (memory: axon tunnel): both block
sizes are traced IN ONE PROCESS (engine._TERMINATION_BLOCK is read at trace
time; the runner cache keys do not include it, so each variant gets a fresh
_build_runner call), repeats interleaved round-robin so tunnel drift
cancels from the ratio, completion forced by scalar readback.

Usage: python tools/measure_block_r5.py [size] [gens] [blocks...]
Writes benchmarks/block_ab_r5.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> int:
    size = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
    gens = int(sys.argv[2]) if len(sys.argv) > 2 else 1000
    blocks = [int(b) for b in sys.argv[3:]] or [16, 64, 128]

    import jax
    import jax.numpy as jnp

    from gol_tpu import engine
    from gol_tpu.config import GameConfig

    assert jax.default_backend() == "tpu", jax.default_backend()
    rng = np.random.default_rng(42)
    words = jnp.asarray(rng.integers(
        0, np.iinfo(np.uint32).max, size=(size, size // 32),
        dtype=np.uint32, endpoint=True,
    ))
    config = GameConfig(gen_limit=gens)

    runners = {}
    for b in blocks:
        engine._TERMINATION_BLOCK = b
        t0 = time.time()
        # _build_runner directly: the lru_cached factories would return the
        # first variant's trace for every block size.
        r = engine._build_runner((size, size), config, None, "packed",
                                 segmented=False, packed_state=True)
        out = r(words)
        g = int(out[1])  # scalar readback = reliable completion barrier
        log(f"  block {b}: compile+first run {time.time() - t0:.0f}s, "
            f"{g} generations")
        runners[b] = r

    reps = 4
    times = {b: [] for b in blocks}
    for rep in range(reps):
        for b in blocks:  # interleaved round-robin
            t0 = time.perf_counter()
            out = runners[b](words)
            g = int(out[1])
            times[b].append(time.perf_counter() - t0)
            log(f"  rep {rep} block {b}: {times[b][-1]:.2f}s")
    best = {b: min(v) for b, v in times.items()}
    rates = {b: size * size * gens / best[b] for b in blocks}
    payload = {
        "what": "engine._TERMINATION_BLOCK A/B on the headline packed-state "
                "run; interleaved repeats in one process, best-of wall",
        "size": size,
        "gen_limit": gens,
        "wall_s": {str(b): [round(t, 3) for t in v] for b, v in times.items()},
        "cells_per_s_best": {str(b): round(r) for b, r in rates.items()},
        "ratio_vs_16": {
            str(b): round(rates[b] / rates[blocks[0]], 4) for b in blocks
        },
    }
    path = os.path.join(REPO, "benchmarks", "block_ab_r5.json")
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(json.dumps(payload["cells_per_s_best"]))
    log("wrote", path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
